"""Layered runtime configuration.

Mirrors the reference's figment-based config (lib/runtime/src/config.rs:72):
defaults <- optional config file (TOML/JSON/YAML) <- `DYN_*` environment
variables. Env takes precedence, like figment's profile layering.

Recognised env prefixes (parity with reference config.rs:214-260):
  DYN_RUNTIME_*   — runtime knobs (worker threads, shutdown timeouts)
  DYN_SYSTEM_*    — system status server (enabled, port)
  DYN_COMPUTE_*   — compute pool sizing
  DYN_HEALTH_CHECK_* — canary health checks
  DYN_DISCOVERY_* — built-in discovery service address
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path
from typing import Any, Optional


def _env(name: str, default: Any = None, cast=str):
    raw = os.environ.get(name)
    if raw is None:
        return default
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return cast(raw)


def env_bool(name: str, default: bool = False) -> bool:
    """Canonical bool parsing for registry-typed env vars: truthy spellings
    are exactly 1/true/yes/on (case-insensitive); anything else is False.
    Every `bool`-typed ENV_REGISTRY read must go through this (or _env) so
    the accepted spellings cannot drift between modules."""
    return bool(_env(name, default, bool))


def env_float(name: str, default: float) -> float:
    """Canonical lenient float parsing for registry-typed env vars: unset,
    empty, or unparseable values fall back to the default with a warning
    (a typo'd knob must degrade, not take the process down). One spelling
    shared by every module (the SLA/sched and gate knob surfaces)."""
    raw = os.environ.get(name)
    if raw in (None, ""):
        return default
    try:
        return float(raw)
    except ValueError:
        import logging

        logging.getLogger(__name__).warning(
            "%s=%r is not a number; using %s", name, raw, default)
        return default


def env_int(name: str, default: int) -> int:
    """Lenient int parsing, same contract as env_float."""
    raw = os.environ.get(name)
    if raw in (None, ""):
        return default
    try:
        return int(raw)
    except ValueError:
        import logging

        logging.getLogger(__name__).warning(
            "%s=%r is not an integer; using %s", name, raw, default)
        return default


@dataclasses.dataclass(frozen=True)
class EnvVar:
    """One registered environment variable: the discoverability contract.

    Every `DYN_*` / `DYNAMO_TPU_*` read anywhere in the package must have
    an entry here — enforced by the `env-registry` dynolint rule
    (dynamo_tpu/analysis). `python -m dynamo_tpu.analysis --emit-env-docs`
    renders the table to docs/configuration.md."""

    name: str
    type: str  # "str" | "int" | "float" | "bool" | "path" | "enum"
    default: Optional[str]
    description: str
    module: str  # primary consuming module (repo-relative)


ENV_REGISTRY: tuple = (
    # -- logging ------------------------------------------------------- #
    EnvVar("DYN_LOG", "str", "info",
           "Log filter, RUST_LOG-style: a level (`debug`) or "
           "`target=level` pairs (`dynamo_tpu.engine=debug,info`).",
           "runtime/logging.py"),
    EnvVar("DYN_LOGGING_JSONL", "bool", "0",
           "Switch log output to JSON lines (one object per record).",
           "runtime/logging.py"),
    # -- runtime / event loop ------------------------------------------ #
    EnvVar("DYN_RUNTIME_CONFIG", "path", None,
           "Optional TOML/JSON/YAML config file layered under the env.",
           "runtime/config.py"),
    EnvVar("DYN_RUNTIME_NUM_WORKER_THREADS", "int", "0",
           "Worker thread count hint; 0 = library default.",
           "runtime/config.py"),
    EnvVar("DYN_RUNTIME_MAX_BLOCKING_THREADS", "int", "4",
           "Cap on blocking-offload threads.",
           "runtime/config.py"),
    EnvVar("DYN_RUNTIME_GRACEFUL_SHUTDOWN_TIMEOUT", "float", "30.0",
           "Seconds to wait for in-flight streams on shutdown.",
           "runtime/config.py"),
    EnvVar("DYN_COMPUTE_THREADS", "int", "min(4, cpus)",
           "Compute-pool size for CPU-bound offload (tokenize/template).",
           "runtime/compute.py"),
    # -- system status / health ---------------------------------------- #
    EnvVar("DYN_SYSTEM_ENABLED", "bool", "0",
           "Enable the system-status HTTP server (health + metrics).",
           "runtime/system_status.py"),
    EnvVar("DYN_SYSTEM_HOST", "str", "0.0.0.0",
           "Bind host for the system-status server.",
           "runtime/system_status.py"),
    EnvVar("DYN_SYSTEM_PORT", "int", "0",
           "Bind port for the system-status server; 0 = ephemeral. An "
           "explicit port implies DYN_SYSTEM_ENABLED=1.",
           "runtime/system_status.py"),
    EnvVar("DYN_HEALTH_CHECK_ENABLED", "bool", "0",
           "Enable canary health checks against served endpoints.",
           "runtime/health_check.py"),
    EnvVar("DYN_HEALTH_CHECK_IDLE_TIMEOUT", "float", "60.0",
           "Seconds of endpoint idleness before a canary probe fires.",
           "runtime/health_check.py"),
    EnvVar("DYN_HEALTH_CHECK_REQUEST_TIMEOUT", "float", "10.0",
           "Canary probe request timeout in seconds.",
           "runtime/health_check.py"),
    # -- discovery / request plane ------------------------------------- #
    EnvVar("DYN_DISCOVERY_ENDPOINT", "str", "tcp://127.0.0.1:2379",
           "Discovery-service address (etcd role).",
           "runtime/discovery.py"),
    EnvVar("DYN_LEASE_TTL_S", "float", "10.0",
           "Instance-lease TTL: missed keepalives past this drop the "
           "worker from discovery.",
           "runtime/discovery.py"),
    EnvVar("DYN_REQUEST_PLANE_HOST", "str", "127.0.0.1",
           "Bind host for the TCP request-plane server.",
           "runtime/request_plane.py"),
    EnvVar("DYN_REQUEST_PLANE_CONNECT_TIMEOUT", "float", "5.0",
           "Connect budget for dialing a worker's request-plane server; "
           "a black-holed address raises StreamLost (retryable) instead "
           "of hanging the caller.",
           "runtime/request_plane.py"),
    EnvVar("DYN_STREAM_COALESCE_MS", "float", "0",
           "Extra milliseconds the worker-side response writer may wait "
           "after the first ready stream item to gather more into one "
           "multi-item request-plane frame. 0 (default) coalesces only "
           "items already queued in the same event-loop tick, adding no "
           "latency; raising it trades TTFT/ITL for fewer, fuller frames.",
           "runtime/request_plane.py"),
    EnvVar("DYN_STREAM_COALESCE_MAX_ITEMS", "int", "64",
           "Cap on stream items packed into one multi-item request-plane "
           "frame (and on token deltas merged per detokenizer batch on "
           "the frontend). Bounds frame size and per-batch latency.",
           "runtime/request_plane.py"),
    EnvVar("DYN_WIRE_BINARY_TOKENS", "bool", "1",
           "Zero-copy token wire path: the request-plane client "
           "advertises ENC_TOK on every stream, and workers answer pure "
           "token-delta batches as packed little-endian u32 payloads "
           "instead of msgpack dicts (per-frame msgpack fallback for "
           "anything the encoding cannot carry). 0 = msgpack everywhere "
           "(the pre-PR-13 wire, and the codec A/B baseline arm).",
           "runtime/request_plane.py"),
    EnvVar("DYN_DETOK_POOL", "bool", "1",
           "Run frontend detokenization batches on the bounded compute "
           "pool instead of the event loop when they are big enough to "
           "amortize the hop (DYN_DETOK_POOL_MIN_TOKENS) or carry a "
           "stop-string scan — one slow stream's scan must not stall "
           "every other stream's SSE writer. 0 = always inline.",
           "llm/backend.py"),
    EnvVar("DYN_DETOK_POOL_MIN_TOKENS", "int", "8",
           "Smallest token-delta batch worth offloading to the compute "
           "pool under DYN_DETOK_POOL (stop-string batches always "
           "offload); smaller batches detokenize inline — the executor "
           "hop would cost more than it frees.",
           "llm/backend.py"),
    # -- fault injection (dynochaos) ----------------------------------- #
    EnvVar("DYN_FAULT_PLAN", "str", None,
           "dynochaos fault plan: `;`-separated `point[:spec,...]` rules "
           "(e.g. `request_plane.frame:sever,after=3;discovery.lease:"
           "drop@t=2.0`). Unset = injection compiled out to a no-op "
           "pass-through. See docs/fault_tolerance.md.",
           "runtime/faults.py"),
    EnvVar("DYN_FAULT_SEED", "int", "0",
           "Seed for probabilistic (`p=`) fault rules — same plan + seed "
           "+ hit sequence fires identically.",
           "runtime/faults.py"),
    EnvVar("DYN_FAULT_DISABLE", "bool", "0",
           "Global dynochaos kill-switch: force the no-op injector even "
           "when DYN_FAULT_PLAN is set.",
           "runtime/faults.py"),
    # -- engine scheduling / SLA (engine/scheduler/, docs/scheduler.md) - #
    EnvVar("DYN_SCHED_POLICY", "enum", "fifo",
           "Engine step-scheduling policy: `fifo` preserves the legacy "
           "admit-order prefill dispatch bit-for-bit (modulo the "
           "batch-kind anti-starvation fairness fix, active under both "
           "policies); `sla` enables the EDF + ITL-budget StepPlanner "
           "(also honored by the CPU mocker's scheduler).",
           "engine/scheduler/sla.py"),
    EnvVar("DYN_SLA_TTFT_MS", "float", "2000",
           "Per-request TTFT target under DYN_SCHED_POLICY=sla: prefill "
           "deadlines are arrival + target, halved per +1 of the "
           "request's nvext.priority. Drives EDF ordering and the disagg "
           "router's local-vs-remote prefill decision.",
           "engine/scheduler/sla.py"),
    EnvVar("DYN_SLA_ITL_MS", "float", "0",
           "Decode ITL budget (ms/token) under DYN_SCHED_POLICY=sla: "
           "prefill dispatches are shrunk or deferred so the projected "
           "per-token latency of decode-block + prefill stays under it. "
           "0 (default) disables the ITL budget.",
           "engine/scheduler/sla.py"),
    # -- SLA planner loop (planner/, docs/autoscaling.md) ---------------- #
    EnvVar("DYN_PLANNER_SCRAPE_TIMEOUT", "float", "5.0",
           "Per-attempt timeout for the planner's frontend /metrics "
           "scrape; a hung endpoint costs one bounded attempt, never the "
           "whole planner loop.",
           "planner/planner_core.py"),
    EnvVar("DYN_PLANNER_SCRAPE_RETRIES", "int", "3",
           "Scrape attempts per adjustment interval (backoff between); "
           "when all fail the planner holds its last decision instead of "
           "feeding NaN/stale averages into the scaling math.",
           "planner/planner_core.py"),
    EnvVar("DYN_PLANNER_METRICS_MAX_AGE_S", "float", "0",
           "Observations older than this never reach a scaling decision "
           "(the planner holds). 0 = 2.5 × the adjustment interval.",
           "planner/planner_core.py"),
    EnvVar("DYN_PLANNER_COOLDOWN_INTERVALS", "int", "1",
           "Intervals the planner holds after an applied replica change "
           "before it may change again — structurally rules out A→B→A "
           "flapping inside the window.",
           "planner/planner_core.py"),
    EnvVar("DYN_PLANNER_MAX_STEP", "int", "2",
           "Bound on the replica delta per decision per role: one noisy "
           "interval can move the fleet at most this far.",
           "planner/planner_core.py"),
    EnvVar("DYN_PLANNER_SCALE_DOWN_STABLE_INTERVALS", "int", "2",
           "Consecutive intervals the model must ask for below-current "
           "capacity before the planner steps down (scale-up is never "
           "hysteresis-gated: restoring SLA outranks fleet stability).",
           "planner/planner_core.py"),
    EnvVar("DYN_PLANNER_WORKERS_PER_FRONTEND", "int", "0",
           "Frontend-role scaling: with N > 0 the planner sizes the "
           "frontend tier to ceil(total workers / N) replicas alongside "
           "every applied worker target (frontends are stateless over "
           "shared discovery, docs/frontend_scaleout.md). 0 = frontends "
           "are not planner-managed (the pre-PR-13 behavior).",
           "planner/planner_core.py"),
    # -- planner role morphing (docs/autoscaling.md "Role morphing") ---- #
    EnvVar("DYN_PLANNER_MORPH", "bool", "1",
           "Re-role arm: under load skew (one role over, the other "
           "under) convert a live worker via morph instead of "
           "cold-spawning, when the priced morph beats spawn on "
           "time-to-SLA-recovery. Effective only when the connector "
           "exposes morph_replicas; 0 = spawn-only (the pre-morph "
           "behavior).",
           "planner/planner_core.py"),
    EnvVar("DYN_PLANNER_MORPH_COST_S", "float", "3.0",
           "Seed estimate of one live morph's wall-clock (drain the "
           "outgoing role + flip + re-warm cached compile surfaces); "
           "refined by the connector's measured morph durations when "
           "available. Compared against DYN_PLANNER_SPAWN_COST_S to "
           "price re-role vs spawn.",
           "planner/planner_core.py"),
    EnvVar("DYN_PLANNER_SPAWN_COST_S", "float", "30.0",
           "Seed estimate of a cold replica spawn's wall-clock (process "
           "start + weight load + full warmup compile drive) for the "
           "re-role pricing; refined by measured spawn-to-ready times "
           "when the connector reports them.",
           "planner/planner_core.py"),
    EnvVar("DYN_PLANNER_COLOCATE", "bool", "0",
           "Colocated-mode arm: at low traffic (both roles' raw asks at "
           "the 1-replica floor for the scale-down-stable window) morph "
           "the decode worker to role `both` and retire the dedicated "
           "prefill replica — small fleets stop paying a dedicated "
           "prefill tax. Scale-up later adds dedicated replicas "
           "normally.",
           "planner/planner_core.py"),
    EnvVar("DYN_MORPH_DRAIN_TIMEOUT_S", "float", "10.0",
           "Engine role-morph drain budget: in-flight outgoing-role "
           "sessions are severed to peers (StreamSevered -> migration) "
           "and must clear the lanes within this window before the flip "
           "proceeds; expiry fails the morph and rolls the role back.",
           "engine/engine.py"),
    # -- frontend admission gate (gate/, docs/overload.md) -------------- #
    EnvVar("DYN_GATE", "bool", "1",
           "dynogate master switch: frontend admission control, per-"
           "tenant fairness and load shedding (docs/overload.md). 0 "
           "compiles the gate out of the frontend — no admission checks, "
           "no metrics subscription, no router watermark preference; "
           "streams are byte-identical to a build without the package.",
           "gate/config.py"),
    EnvVar("DYN_GATE_TTFT_MS", "float", "0",
           "Base TTFT target (ms) for admission-class math; each +1 of "
           "nvext.priority halves it (the SlaConfig.deadline math). 0 "
           "(default) inherits DYN_SLA_TTFT_MS so the edge and the "
           "worker scheduler agree on what on-time means.",
           "gate/config.py"),
    EnvVar("DYN_GATE_TTFT_HEADROOM", "float", "1.5",
           "Admission ceiling multiplier: a request is rejected (429 + "
           "Retry-After, before tokenization) when the fleet's projected "
           "TTFT exceeds headroom x its class target — serving it would "
           "blow its SLA anyway.",
           "gate/config.py"),
    EnvVar("DYN_GATE_QUEUE_WATERMARK", "int", "16",
           "Per-instance queue-depth watermark: PushRouter prefers "
           "instances below it for new streams, and admission projects "
           "TTFT from depth/watermark for workers that publish no "
           "sched_est_ttft_ms estimate (fifo-policy fleets).",
           "gate/signals.py"),
    EnvVar("DYN_GATE_MAX_QUEUE", "int", "64",
           "Gate queue bound: past it waiting admissions are SHED, "
           "lowest SLA class first (newest first within a class). 0 "
           "disables the bound (shedding then happens only on the "
           "per-request wait cap).",
           "gate/gate.py"),
    EnvVar("DYN_GATE_MAX_WAIT_MS", "float", "1000",
           "Cap (ms) on how long a request may park in the gate queue "
           "awaiting capacity; the effective bound is min(this, class "
           "headroom) — waiting past either would blow the SLA it was "
           "queued to protect.",
           "gate/gate.py"),
    EnvVar("DYN_GATE_TENANT_HEADER", "str", "x-dynamo-tenant",
           "HTTP header carrying the tenant key for fairness accounting "
           "(rides PreprocessedRequest.tenant to the worker scheduler's "
           "fairness tiebreak). Absent header = tenant 'default'.",
           "gate/config.py"),
    EnvVar("DYN_GATE_TENANT_RATE", "float", "0",
           "Per-tenant token-bucket rate limit (requests/s) enforced at "
           "admission; a tenant past its bucket gets 429 with "
           "Retry-After = its exact refill time. 0 = unlimited.",
           "gate/config.py"),
    EnvVar("DYN_GATE_TENANT_BURST", "float", "0",
           "Token-bucket burst size per tenant; 0 = max(2 x rate, 1).",
           "gate/config.py"),
    EnvVar("DYN_GATE_TENANT_WEIGHTS", "str", None,
           "WFQ weights per tenant (`gold=4,free=1`): under contention a "
           "tenant drains the gate queue at weight-proportional share. "
           "Unlisted tenants weigh 1.",
           "gate/config.py"),
    EnvVar("DYN_GATE_SIGNAL_TTL_S", "float", "5.0",
           "Load-signal staleness bound: samples older than this are "
           "invisible to admission (a stale fleet view must admit, "
           "never reject on ghosts — the disagg queue_depth_ttl_s rule).",
           "gate/config.py"),
    EnvVar("DYN_GATE_RETRY_AFTER_FLOOR_S", "float", "1.0",
           "Minimum Retry-After (s) on any gate 429.",
           "gate/config.py"),
    # -- engine / memory sizing ---------------------------------------- #
    EnvVar("DYN_HBM_UTILIZATION", "float", "0.85",
           "Fraction of device memory the KV pool auto-sizer may plan "
           "for (the gpu_memory_utilization role).",
           "engine/engine.py"),
    EnvVar("DYN_HBM_BYTES", "int", None,
           "Device memory override in bytes for platforms without "
           "memory_stats (CPU, tunneled runtimes).",
           "engine/engine.py"),
    EnvVar("DYN_HBM_RESERVE_MB", "float", "512",
           "Memory held back for compile/activation workspace the "
           "post-weights snapshot cannot see.",
           "engine/engine.py"),
    EnvVar("DYN_WORKERS_PER_DEVICE", "int", "1",
           "Split the free KV pool between co-located workers sharing "
           "one chip (single-chip disagg).",
           "engine/engine.py"),
    # -- workers / models / native ------------------------------------- #
    EnvVar("DYN_WORKER_INDEX", "int", None,
           "Set by the planner for each spawned worker: its index within "
           "its role's replica set.",
           "planner/connector.py"),
    EnvVar("DYN_HF_ALLOW_DOWNLOAD", "bool", "0",
           "Allow model loads to hit the HuggingFace hub; default is "
           "cache-only (serving environments are often airgapped).",
           "models/loader.py"),
    EnvVar("DYN_NATIVE", "bool", "1",
           "Set to 0 to disable the optional native (C) extension and "
           "force the pure-Python paths.",
           "native/__init__.py"),
    EnvVar("DYNAMO_TPU_COMPILE_CACHE", "path", "~/.cache/dynamo_tpu_xla",
           "Persistent XLA compilation-cache directory; 'off' disables.",
           "engine/engine.py"),
    EnvVar("DYNAMO_TPU_PAGED_ATTN", "enum", "auto",
           "Paged-attention kernel selection: auto / pallas / xla "
           "reference. One gate (`_pallas_eligible`) covers the prefill, "
           "decode, and ragged mixed-step kernels.",
           "ops/paged_attention.py"),
    EnvVar("DYN_MIXED_DISPATCH", "bool", "1",
           "Ragged unified mixed dispatch: fuse the step's prefill chunks "
           "and active decode lanes into one device call "
           "(docs/ragged_attention.md). EngineConfig.mixed_dispatch "
           "overrides.",
           "engine/engine.py"),
    EnvVar("DYN_LORA_POOL_SLOTS", "int", "8",
           "Device slots in the LoRA adapter tier (models/lora_pool.py): "
           "the fixed-size HBM adapter stack pages against the host "
           "roster, LRU-evicting unpinned adapters on a cold acquire "
           "(docs/multi_lora.md). Fixed N keeps adapter churn from ever "
           "recompiling a dispatch variant.",
           "engine/engine.py"),
    EnvVar("DYN_KV_QUANT", "enum", "none",
           "Quantized KV cache page format: `none` (fp, the seed's exact "
           "byte-identical path), `int8`, or `int4` (two tokens per byte "
           "along the page axis). Pages quantize ON WRITE with "
           "per-page-per-head f32 scales and dequantize inside the "
           "attention kernels' VMEM window (scales ride scalar prefetch "
           "beside the page tables); the auto-sized HBM pool, the KVBM "
           "G2/G3 tiers and every peer-pull/disagg payload shrink "
           "~2x/4x, roughly doubling resident sessions at fixed HBM. "
           "Every worker of a fleet must run the SAME format — "
           "mismatches fail typed (KvFormatError), counted in "
           "kv_format_mismatches. EngineConfig.kv_quant overrides. "
           "Requires tp/pp/sp == 1.",
           "ops/kv_quant.py"),
    # -- KVBM tier pipeline (kvbm/, docs/kvbm.md) ----------------------- #
    EnvVar("DYN_KVBM_PIPELINE", "bool", "1",
           "Batched KVBM offload pipeline: coalesce a step's block "
           "commits into one device gather and run tier stores on the "
           "dedicated kvbm-tier thread. 0 restores the inline "
           "per-commit offload (one gather + store per commit, all on "
           "the device executor) — the bench_kv_cache.py before/after "
           "arm and a safety valve.",
           "kvbm/manager.py"),
    EnvVar("DYN_KVBM_OFFLOAD_QUEUE", "int", "8",
           "Max in-flight offload batches between the per-step gather "
           "and the kvbm-tier thread's stores. When the tier thread "
           "falls behind, the OLDEST queued batch is dropped (counted "
           "in kvbm_offload_blocks_dropped) instead of stalling the "
           "step loop — offloads are cache copies, never correctness.",
           "kvbm/manager.py"),
    EnvVar("DYN_KV_INCREMENTAL_COMMIT", "bool", "1",
           "Durable decode sessions: commit newly-full generated KV "
           "blocks DURING the step loop (prefix cache + KVBM offload + "
           "announcement mesh + session checkpointing see a live "
           "session's prefix as it grows) instead of only at slot "
           "release. Commits are byte-identical either way; 0 restores "
           "the release-only arm.",
           "engine/engine.py"),
    EnvVar("DYN_KV_CHECKPOINT", "str", "off",
           "Session KV checkpointing (kvbm/checkpoint.py): replicate "
           "committed session blocks to a peer worker's G2 over the KV "
           "data plane so a worker death loses only the un-checkpointed "
           "tail — the survivor onboards the replicated prefix and "
           "recomputes the rest. Value = max staged blocks (bounded "
           "queue refusing the newest on overflow — the replicated "
           "prefix stays contiguous; same never-stall discipline as "
           "DYN_KVBM_OFFLOAD_QUEUE); 'off' (default) compiles the path "
           "out entirely.",
           "kvbm/checkpoint.py"),
    EnvVar("DYN_KVBM_PEER_PULL", "bool", "1",
           "Cluster KV fabric: let admission onboard blocks from a PEER "
           "worker's G2/G3 tiers over the KV data plane (announcement "
           "mesh owner, or the router's kv_holder hint), arbitrated by "
           "the three-arm onboard budget — per-peer transfer-rate EWMA "
           "vs local-tier load vs recompute. 0 = local tiers only "
           "(pre-fabric behavior).",
           "kvbm/manager.py"),
    EnvVar("DYN_DISAGG_STREAM", "bool", "1",
           "Streamed disagg prefill→decode handoff: the prefill worker "
           "stages the transfer at ADMISSION and publishes KV chunks as "
           "prefill commits pages, so the decode worker's pull overlaps "
           "prefill compute and its first decode step dispatches as soon "
           "as the last chunk + first token land. 0 = serial handoff "
           "(descriptor ships only after prefill completes).",
           "jax_worker/disagg_handler.py"),
    EnvVar("DYN_KVBM_EVICTION", "enum", "lru",
           "KVBM tier eviction policy: `lru`, `lfu`, or `prefix-aware` "
           "(protects blocks with live chained descendants in the same "
           "tier — the RTP-LLM/Mooncake heuristic). One value applies "
           "to both tiers; `host=lfu,disk=lru` sets them independently.",
           "kvbm/manager.py"),
    # -- KV router index bound (llm/kv_router/, docs/kv_cache_routing.md) #
    EnvVar("DYN_ROUTER_INDEX_MAX_BLOCKS", "int", "0",
           "Block-count cap per KV-router index (KvIndexer tree; "
           "KvIndexerSharded ceil-splits it statically across shards, "
           "so with fewer workers than shards the effective cap is "
           "proportionally lower — the memory bound always holds, the "
           "hit-rate errs conservative). Past the cap, leaves are "
           "evicted least-recently-matched first, so the index degrades "
           "from the deep cold end of each prefix chain instead of "
           "OOMing the frontend. 0 = unbounded (seed behavior; keeps "
           "the native C++ index eligible).",
           "llm/kv_router/indexer.py"),
)


@dataclasses.dataclass
class RuntimeConfig:
    """Process-local runtime configuration (reference: RuntimeConfig config.rs:72)."""

    # asyncio / compute pool
    num_worker_threads: int = 0  # 0 = library default
    max_blocking_threads: int = 4
    # graceful shutdown
    graceful_shutdown_timeout: float = 30.0
    # system status server (reference: DYN_SYSTEM_ENABLED/DYN_SYSTEM_PORT)
    system_enabled: bool = False
    system_host: str = "0.0.0.0"
    system_port: int = 0  # 0 = ephemeral
    # health checks (reference: config.rs:155-167)
    health_check_enabled: bool = False
    health_check_idle_timeout: float = 60.0
    health_check_request_timeout: float = 10.0
    # built-in discovery service ("etcd" role)
    discovery_endpoint: str = "tcp://127.0.0.1:2379"
    # instance-lease TTL: how long after missed keepalives a worker drops
    # out of discovery (reference etcd lease, transports/etcd.rs:43). Raise
    # on heavily-contended hosts where event loops can starve past 10s.
    lease_ttl_s: float = 10.0
    # request-plane bind host for TCP response/request streams
    request_plane_host: str = "127.0.0.1"
    # connect budget for dialing a worker (black-holed address -> StreamLost)
    request_plane_connect_timeout: float = 5.0

    @classmethod
    def from_settings(cls, config_path: Optional[str] = None) -> "RuntimeConfig":
        """Layered load: defaults <- file <- env (reference figment() config.rs:214)."""
        cfg = cls()
        path = config_path or os.environ.get("DYN_RUNTIME_CONFIG")
        if path and Path(path).exists():
            text = Path(path).read_text()
            data: dict
            if path.endswith((".yaml", ".yml")):
                import yaml

                data = yaml.safe_load(text) or {}
            else:
                data = json.loads(text)
            for field in dataclasses.fields(cls):
                if field.name in data:
                    setattr(cfg, field.name, data[field.name])
        # env layer
        cfg.num_worker_threads = _env(
            "DYN_RUNTIME_NUM_WORKER_THREADS", cfg.num_worker_threads, int
        )
        cfg.max_blocking_threads = _env(
            "DYN_RUNTIME_MAX_BLOCKING_THREADS", cfg.max_blocking_threads, int
        )
        cfg.graceful_shutdown_timeout = _env(
            "DYN_RUNTIME_GRACEFUL_SHUTDOWN_TIMEOUT", cfg.graceful_shutdown_timeout, float
        )
        cfg.system_enabled = _env("DYN_SYSTEM_ENABLED", cfg.system_enabled, bool)
        cfg.system_host = _env("DYN_SYSTEM_HOST", cfg.system_host)
        cfg.system_port = _env("DYN_SYSTEM_PORT", cfg.system_port, int)
        if cfg.system_port > 0 and "DYN_SYSTEM_ENABLED" not in os.environ:
            # an explicit port IS the ask (the deploy/metrics prometheus
            # scrape targets it); requiring a second flag to turn the
            # server on makes the gauges silently absent. An explicit
            # DYN_SYSTEM_ENABLED=0 still wins.
            cfg.system_enabled = True
        cfg.health_check_enabled = _env(
            "DYN_HEALTH_CHECK_ENABLED", cfg.health_check_enabled, bool
        )
        cfg.health_check_idle_timeout = _env(
            "DYN_HEALTH_CHECK_IDLE_TIMEOUT", cfg.health_check_idle_timeout, float
        )
        cfg.health_check_request_timeout = _env(
            "DYN_HEALTH_CHECK_REQUEST_TIMEOUT", cfg.health_check_request_timeout, float
        )
        cfg.discovery_endpoint = _env("DYN_DISCOVERY_ENDPOINT", cfg.discovery_endpoint)
        cfg.lease_ttl_s = _env("DYN_LEASE_TTL_S", cfg.lease_ttl_s, float)
        cfg.request_plane_host = _env("DYN_REQUEST_PLANE_HOST", cfg.request_plane_host)
        cfg.request_plane_connect_timeout = _env(
            "DYN_REQUEST_PLANE_CONNECT_TIMEOUT", cfg.request_plane_connect_timeout, float
        )
        return cfg


def discovery_address(cfg: Optional[RuntimeConfig] = None) -> tuple[str, int]:
    """Parse the discovery endpoint into (host, port)."""
    cfg = cfg or RuntimeConfig.from_settings()
    ep = cfg.discovery_endpoint
    if "://" in ep:
        ep = ep.split("://", 1)[1]
    host, _, port = ep.rpartition(":")
    return host or "127.0.0.1", int(port)
