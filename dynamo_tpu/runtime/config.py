"""Layered runtime configuration.

Mirrors the reference's figment-based config (lib/runtime/src/config.rs:72):
defaults <- optional config file (TOML/JSON/YAML) <- `DYN_*` environment
variables. Env takes precedence, like figment's profile layering.

Recognised env prefixes (parity with reference config.rs:214-260):
  DYN_RUNTIME_*   — runtime knobs (worker threads, shutdown timeouts)
  DYN_SYSTEM_*    — system status server (enabled, port)
  DYN_COMPUTE_*   — compute pool sizing
  DYN_HEALTH_CHECK_* — canary health checks
  DYN_DISCOVERY_* — built-in discovery service address
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path
from typing import Any, Optional


def _env(name: str, default: Any = None, cast=str):
    raw = os.environ.get(name)
    if raw is None:
        return default
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return cast(raw)


@dataclasses.dataclass
class RuntimeConfig:
    """Process-local runtime configuration (reference: RuntimeConfig config.rs:72)."""

    # asyncio / compute pool
    num_worker_threads: int = 0  # 0 = library default
    max_blocking_threads: int = 4
    # graceful shutdown
    graceful_shutdown_timeout: float = 30.0
    # system status server (reference: DYN_SYSTEM_ENABLED/DYN_SYSTEM_PORT)
    system_enabled: bool = False
    system_host: str = "0.0.0.0"
    system_port: int = 0  # 0 = ephemeral
    # health checks (reference: config.rs:155-167)
    health_check_enabled: bool = False
    health_check_idle_timeout: float = 60.0
    health_check_request_timeout: float = 10.0
    # built-in discovery service ("etcd" role)
    discovery_endpoint: str = "tcp://127.0.0.1:2379"
    # instance-lease TTL: how long after missed keepalives a worker drops
    # out of discovery (reference etcd lease, transports/etcd.rs:43). Raise
    # on heavily-contended hosts where event loops can starve past 10s.
    lease_ttl_s: float = 10.0
    # request-plane bind host for TCP response/request streams
    request_plane_host: str = "127.0.0.1"

    @classmethod
    def from_settings(cls, config_path: Optional[str] = None) -> "RuntimeConfig":
        """Layered load: defaults <- file <- env (reference figment() config.rs:214)."""
        cfg = cls()
        path = config_path or os.environ.get("DYN_RUNTIME_CONFIG")
        if path and Path(path).exists():
            text = Path(path).read_text()
            data: dict
            if path.endswith((".yaml", ".yml")):
                import yaml

                data = yaml.safe_load(text) or {}
            else:
                data = json.loads(text)
            for field in dataclasses.fields(cls):
                if field.name in data:
                    setattr(cfg, field.name, data[field.name])
        # env layer
        cfg.num_worker_threads = _env(
            "DYN_RUNTIME_NUM_WORKER_THREADS", cfg.num_worker_threads, int
        )
        cfg.max_blocking_threads = _env(
            "DYN_RUNTIME_MAX_BLOCKING_THREADS", cfg.max_blocking_threads, int
        )
        cfg.graceful_shutdown_timeout = _env(
            "DYN_RUNTIME_GRACEFUL_SHUTDOWN_TIMEOUT", cfg.graceful_shutdown_timeout, float
        )
        cfg.system_enabled = _env("DYN_SYSTEM_ENABLED", cfg.system_enabled, bool)
        cfg.system_host = _env("DYN_SYSTEM_HOST", cfg.system_host)
        cfg.system_port = _env("DYN_SYSTEM_PORT", cfg.system_port, int)
        if cfg.system_port > 0 and "DYN_SYSTEM_ENABLED" not in os.environ:
            # an explicit port IS the ask (the deploy/metrics prometheus
            # scrape targets it); requiring a second flag to turn the
            # server on makes the gauges silently absent. An explicit
            # DYN_SYSTEM_ENABLED=0 still wins.
            cfg.system_enabled = True
        cfg.health_check_enabled = _env(
            "DYN_HEALTH_CHECK_ENABLED", cfg.health_check_enabled, bool
        )
        cfg.health_check_idle_timeout = _env(
            "DYN_HEALTH_CHECK_IDLE_TIMEOUT", cfg.health_check_idle_timeout, float
        )
        cfg.health_check_request_timeout = _env(
            "DYN_HEALTH_CHECK_REQUEST_TIMEOUT", cfg.health_check_request_timeout, float
        )
        cfg.discovery_endpoint = _env("DYN_DISCOVERY_ENDPOINT", cfg.discovery_endpoint)
        cfg.lease_ttl_s = _env("DYN_LEASE_TTL_S", cfg.lease_ttl_s, float)
        cfg.request_plane_host = _env("DYN_REQUEST_PLANE_HOST", cfg.request_plane_host)
        return cfg


def discovery_address(cfg: Optional[RuntimeConfig] = None) -> tuple[str, int]:
    """Parse the discovery endpoint into (host, port)."""
    cfg = cfg or RuntimeConfig.from_settings()
    ep = cfg.discovery_endpoint
    if "://" in ep:
        ep = ep.split("://", 1)[1]
    host, _, port = ep.rpartition(":")
    return host or "127.0.0.1", int(port)
