"""Structured logging + W3C trace-context propagation.

Mirrors reference lib/runtime/src/logging.rs: env-filtered subscriber
(`DYN_LOG`, like RUST_LOG), optional JSON line output (`DYN_LOGGING_JSONL`),
and `traceparent` propagation across process hops
(DistributedTraceContext logging.rs:138, parse_traceparent :168).
"""

from __future__ import annotations

import contextvars
import dataclasses
import json
import logging
import os
import secrets
import sys
import time
from typing import Optional

_TRACE_CTX: contextvars.ContextVar[Optional["DistributedTraceContext"]] = (
    contextvars.ContextVar("dyn_trace_ctx", default=None)
)


@dataclasses.dataclass(frozen=True)
class DistributedTraceContext:
    """W3C trace-context carried across NATS/TCP hops (reference logging.rs:138)."""

    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    flags: str = "01"

    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"

    def child(self) -> "DistributedTraceContext":
        return DistributedTraceContext(self.trace_id, secrets.token_hex(8), self.flags)

    @classmethod
    def new_root(cls) -> "DistributedTraceContext":
        return cls(secrets.token_hex(16), secrets.token_hex(8))


def parse_traceparent(header: str) -> Optional[DistributedTraceContext]:
    """Parse `00-<trace_id>-<span_id>-<flags>` (reference logging.rs:168)."""
    parts = header.strip().split("-")
    if len(parts) != 4 or parts[0] != "00":
        return None
    _, trace_id, span_id, flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return DistributedTraceContext(trace_id, span_id, flags)


def current_trace() -> Optional[DistributedTraceContext]:
    return _TRACE_CTX.get()


def set_trace(ctx: Optional[DistributedTraceContext]):
    _TRACE_CTX.set(ctx)


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        trace = current_trace()
        if trace is not None:
            entry["trace_id"] = trace.trace_id
            entry["span_id"] = trace.span_id
        if record.exc_info:
            entry["exception"] = self.formatException(record.exc_info)
        return json.dumps(entry)


class _TextFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        trace = current_trace()
        if trace is not None:
            base += f" trace_id={trace.trace_id[:8]}"
        return base


_INITIALIZED = False


def init_logging(level: Optional[str] = None, jsonl: Optional[bool] = None):
    """Install the root handler once. `DYN_LOG` sets the filter (like RUST_LOG);
    `DYN_LOGGING_JSONL=1` switches to JSON-lines output."""
    global _INITIALIZED
    if _INITIALIZED:
        return
    _INITIALIZED = True
    level = level or os.environ.get("DYN_LOG", "info")
    if jsonl is None:
        from .config import env_bool

        jsonl = env_bool("DYN_LOGGING_JSONL")
    handler = logging.StreamHandler(sys.stderr)
    if jsonl:
        handler.setFormatter(_JsonFormatter())
    else:
        handler.setFormatter(
            _TextFormatter("%(asctime)s %(levelname)-5s %(name)s: %(message)s")
        )
    root = logging.getLogger()
    root.addHandler(handler)
    base_level = level.split(",")[0].strip().upper()
    try:
        root.setLevel(base_level)
    except ValueError:
        root.setLevel(logging.INFO)
    # per-target directives: "info,dynamo_tpu.runtime=debug"
    for directive in level.split(",")[1:]:
        if "=" in directive:
            target, lvl = directive.split("=", 1)
            logging.getLogger(target.strip()).setLevel(lvl.strip().upper())
