"""Compute pool: CPU-bound work off the event loop.

The reference bridges a rayon thread pool into tokio so tokenization and
template rendering never stall the async runtime (lib/runtime/src/compute/
pool.rs, compute/mod.rs:31). Python analogue: a bounded ThreadPoolExecutor
shared process-wide — HF tokenizers release the GIL in their Rust core, so
encode work genuinely runs beside the event loop; pure-Python fallbacks
(byte tokenizer) still yield the loop between bytecodes instead of
monopolizing it for an entire long prompt.

Sizing: DYN_COMPUTE_THREADS env, default min(4, cpus). A request-serving
frontend should never need more — the pool exists for latency isolation,
not throughput.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import os
from typing import Any, Callable, Optional


class ComputePool:
    """Process-wide pool for tokenize/template/detok offload."""

    _instance: Optional["ComputePool"] = None

    def __init__(self, threads: Optional[int] = None):
        n = threads or int(
            os.environ.get("DYN_COMPUTE_THREADS")
            or min(4, os.cpu_count() or 1)
        )
        self.threads = max(1, n)
        self._exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.threads, thread_name_prefix="dyn-compute"
        )
        self.tasks_run = 0

    @classmethod
    def get(cls) -> "ComputePool":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    async def run(self, fn: Callable[..., Any], *args: Any) -> Any:
        self.tasks_run += 1
        return await asyncio.get_running_loop().run_in_executor(
            self._exec, fn, *args
        )

    def stats(self) -> dict:
        return {"compute_threads": self.threads, "compute_tasks_run": self.tasks_run}
