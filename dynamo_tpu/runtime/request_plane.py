"""TCP request plane: how requests reach workers and responses stream back.

Reference design: request goes over NATS to the instance's subject, the
response streams back over a direct TCP connection to the caller's
TcpStreamServer (addressed_router.rs:52-142, push_endpoint.rs:36).

dynamo-tpu collapses both hops into one direct TCP connection: each worker
process runs ONE `RequestPlaneServer` exposing all of its endpoints,
registered in discovery as `host:port` + subject. Callers hold pooled
connections and multiplex many in-flight streams on each. This removes the
broker round-trip from the token hot path — on TPU pods, hosts talk
directly over DCN anyway.

Wire protocol (two-part frames, codec.py):
  request :  {t:"req", stream:<id>, subject:<str>, traceparent?:<str>}  + payload
  cancel  :  {t:"cancel", stream:<id>, kill:<bool>}
  response:  {t:"data", stream:<id>} + payload        (one stream item)
             {t:"data", stream:<id>, n:<k>} + payload (k coalesced items,
                                                       payload = packed list)
             {t:"done", stream:<id>}                  (clean end)
             {t:"err",  stream:<id>, error:<str>}     (terminal error)
  liveness:  {t:"ping", stream:<id>} -> {t:"pong", stream:<id>}

Tag spellings are the constants in codec.py's FRAME_TAGS registry
(docs/wire_protocol.md); the flow-frame-protocol lint keeps producer and
consumer arms symmetric.

Token-path batching: the response writer gathers every stream item that is
already ready (same event-loop tick, optionally up to DYN_STREAM_COALESCE_MS
longer) into ONE multi-item frame — one msgpack pack, one corked write — so
steady-state decode pays O(1) serving-plane work per engine dispatch instead
of per token. Item order is preserved; a frame is committed atomically
(a mid-stream sever loses whole frames, never splits one), so migration's
contiguity accounting is unchanged.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import socket as _socket
import time
from typing import Any, AsyncIterator, Awaitable, Callable, Dict, Optional, Tuple

from . import codec, faults
from .codec import (
    ENC_TOK,
    ERR_DEADLINE,
    ERR_DRAINING,
    T_CANCEL,
    T_DATA,
    T_DONE,
    T_ERR,
    T_LOST,
    T_PING,
    T_PONG,
    T_REQ,
)
from .config import _env
from .engine import Context
from .logging import DistributedTraceContext, current_trace, parse_traceparent, set_trace

logger = logging.getLogger(__name__)

Handler = Callable[[Any, Context], AsyncIterator[Any]]

#: back-compat alias — the registered spelling lives in codec.ERR_CODES
DRAINING = ERR_DRAINING


def tune_transport(writer: asyncio.StreamWriter):
    """TCP_NODELAY + bounded write buffer on a request-plane socket.

    Token frames are small and latency-critical — Nagle can hold one back
    a full RTT waiting for an ACK; the high-water mark makes drain() block
    against a stalled peer instead of buffering frames unbounded in
    userspace."""
    sock = writer.get_extra_info("socket")
    if sock is not None:
        try:
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        except (OSError, AttributeError):
            pass  # unix sockets / test doubles have no TCP layer
    try:
        writer.transport.set_write_buffer_limits(high=1 << 20)
    except (AttributeError, RuntimeError, NotImplementedError):
        pass


class EndpointStats:
    """Per-endpoint counters, scraped by metrics + KV-router metrics
    aggregation (reference: NATS $SRV.STATS scraping, transports/nats.rs:107)."""

    def __init__(self):
        self.requests_total = 0
        self.requests_active = 0
        self.errors_total = 0
        # coalescing visibility: items/frames > 1 means the writer is
        # batching; the router/planner metrics topic republishes these so
        # hardware e2e rows self-diagnose serving-plane overhead
        self.frames_total = 0
        self.items_total = 0
        # zero-copy token path visibility: frames that rode the ENC_TOK
        # binary payload instead of msgpack (docs/wire_protocol.md)
        self.frames_binary = 0
        self.last_request_at = time.monotonic()  # idle tracking (health canary)
        self.data = {}  # engine-published stats blob (ForwardPassMetrics)

    def snapshot(self) -> dict:
        return {
            "requests_total": self.requests_total,
            "requests_active": self.requests_active,
            "errors_total": self.errors_total,
            "frames_total": self.frames_total,
            "items_total": self.items_total,
            "frames_binary": self.frames_binary,
            "data": self.data,
        }


class RequestPlaneServer:
    """Per-process TCP server hosting all served endpoints
    (reference: Ingress/PushEndpoint push_endpoint.rs:36)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host, self.port = host, port
        self._handlers: Dict[str, Handler] = {}
        self._stats: Dict[str, EndpointStats] = {}
        self._server: Optional[asyncio.base_events.Server] = None
        self._active: Dict[Tuple[asyncio.StreamWriter, int], Context] = {}
        self._connections: set = set()
        self._draining = False
        # read per-server (not at import) so test clusters can set the env
        # after the module is loaded
        self.coalesce_s = max(_env("DYN_STREAM_COALESCE_MS", 0.0, float), 0.0) / 1e3
        self.coalesce_max = max(_env("DYN_STREAM_COALESCE_MAX_ITEMS", 64, int), 1)

    @property
    def active_streams(self) -> int:
        return len(self._active)

    def register(self, subject: str, handler: Handler) -> EndpointStats:
        self._handlers[subject] = handler
        self._stats[subject] = EndpointStats()
        return self._stats[subject]

    def unregister(self, subject: str):
        self._handlers.pop(subject, None)
        self._stats.pop(subject, None)

    def stats(self, subject: str) -> Optional[EndpointStats]:
        return self._stats.get(subject)

    def all_stats(self) -> Dict[str, dict]:
        return {s: st.snapshot() for s, st in self._stats.items()}

    async def start(self) -> Tuple[str, int]:
        self._server = await asyncio.start_server(self._serve_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.host, self.port

    async def drain(self, timeout: float) -> bool:
        """Graceful-shutdown step 2 and 3 (step 1, lease revocation, is the
        runtime's job): stop accepting NEW streams — the listening socket
        closes and connected callers get a `draining` error they treat as
        StreamLost — then wait up to `timeout` for in-flight streams to
        finish. Returns True when fully drained; False means survivors
        remain for stop() to force-kill."""
        self._draining = True
        if self._server:
            self._server.close()
        deadline = time.monotonic() + timeout
        while self._active and time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        return not self._active

    async def stop(self):
        for ctx in self._active.values():
            ctx.kill()
        if self._server:
            self._server.close()
        for writer in list(self._connections):
            writer.close()
        if self._server:
            await self._server.wait_closed()

    async def _serve_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        tune_transport(writer)
        write_lock = asyncio.Lock()
        tasks: Dict[int, asyncio.Task] = {}
        self._connections.add(writer)
        try:
            while True:
                frame = await codec.read_frame(reader)
                if frame is None:
                    break
                control, payload = frame
                t = control.get("t")
                if t == T_REQ:
                    stream_id = control["stream"]
                    if self._draining:
                        async with write_lock:
                            await codec.write_frame(writer, {
                                "t": T_ERR, "stream": stream_id,
                                "code": ERR_DRAINING,
                                "error": "worker draining: not accepting new streams",
                            })
                        continue
                    task = asyncio.create_task(
                        self._run_stream(control, payload, writer, write_lock)
                    )
                    tasks[stream_id] = task
                    task.add_done_callback(lambda _, sid=stream_id: tasks.pop(sid, None))
                elif t == T_CANCEL:
                    ctx = self._active.get((writer, control["stream"]))
                    if ctx is not None:
                        if control.get("kill"):
                            ctx.kill()
                        else:
                            ctx.stop_generating()
                elif t == T_PING:
                    async with write_lock:
                        # echo the stream id so the pinger's reply queue
                        # (RequestPlaneClient.ping) can route the pong
                        await codec.write_frame(
                            writer,
                            {"t": T_PONG, "stream": control.get("stream")},
                        )
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except ValueError as e:
            logger.warning("dropping connection speaking a bad protocol: %s", e)
        finally:
            for task in tasks.values():
                task.cancel()
            for (w, sid), ctx in list(self._active.items()):
                if w is writer:
                    ctx.kill()
                    self._active.pop((w, sid), None)
            self._connections.discard(writer)
            writer.close()

    async def _run_stream(
        self,
        control: dict,
        payload: bytes,
        writer: asyncio.StreamWriter,
        write_lock: asyncio.Lock,
    ):
        stream_id = control["stream"]
        subject = control.get("subject", "")
        handler = self._handlers.get(subject)
        stats = self._stats.get(subject)
        # zero-copy token path negotiation: the caller's T_REQ advertises
        # `bin` when it can decode ENC_TOK payloads; the writer loop below
        # then ships pure token-delta batches as packed u32s instead of
        # msgpack dicts, falling back per frame for anything else
        want_binary = bool(control.get("bin"))

        async def send(ctrl: dict, pl: bytes = b""):
            ctrl["stream"] = stream_id
            async with write_lock:
                await codec.write_frame(writer, ctrl, pl)

        if handler is None:
            await send({"t": T_ERR, "error": f"no such endpoint: {subject}"})
            return

        ctx = Context(id=control.get("ctx_id"))
        deadline_ms = control.get("deadline_ms")
        if deadline_ms is not None:
            # the caller's remaining budget, rebased onto this host's clock
            ctx.set_deadline(max(0.0, deadline_ms / 1000.0))
        self._active[(writer, stream_id)] = ctx
        tp = control.get("traceparent")
        if tp:
            parsed = parse_traceparent(tp)
            if parsed:
                set_trace(parsed.child())
        if stats:
            stats.requests_total += 1
            stats.requests_active += 1
            stats.last_request_at = time.monotonic()
        # response coalescing: a pump task drains the handler while the
        # writer loop below packs every already-ready item into ONE
        # multi-item frame. The engine emits a whole decode block between
        # event-loop ticks, so steady state is one frame per dispatch, not
        # one per token. DYN_STREAM_COALESCE_MS (default 0) optionally
        # waits a bounded window for more items — off by default so a slow
        # stream's TTFT/ITL is untouched.
        _DATA, _DONE, _ERR = 0, 1, 2
        queue: asyncio.Queue = asyncio.Queue()

        async def pump():
            try:
                async for item in handler(request, ctx):
                    if ctx.is_killed():
                        break
                    queue.put_nowait((_DATA, item))
                queue.put_nowait((_DONE, None))
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — forwarded to the caller
                queue.put_nowait((_ERR, e))

        pump_task: Optional[asyncio.Task] = None
        try:
            request = codec.unpack(payload)
            pump_task = asyncio.create_task(pump())
            terminal: Optional[tuple] = None
            while terminal is None:
                kind, item = await queue.get()
                if kind != _DATA:
                    terminal = (kind, item)
                    break
                items = [item]
                waited = self.coalesce_s <= 0.0
                while len(items) < self.coalesce_max:
                    try:
                        kind, item = queue.get_nowait()
                    except asyncio.QueueEmpty:
                        if waited:
                            break
                        waited = True
                        await asyncio.sleep(self.coalesce_s)
                        continue
                    if kind != _DATA:
                        terminal = (kind, item)
                        break
                    items.append(item)
                if stats:
                    stats.items_total += len(items)
                pos = 0
                if want_binary:
                    # leading run of pure token deltas (of one wrapper
                    # shape) rides ENC_TOK: the steady-state decode frame
                    # is one flat u32 pack, no per-item dict encode (and
                    # ONE merged dict to decode caller-side); the
                    # remainder — typically just the finish item — falls
                    # back to msgpack below
                    packed = codec.try_pack_token_run(items)
                    if packed is not None:
                        payload_bin, pos = packed
                        if stats:
                            stats.frames_total += 1
                            stats.frames_binary += 1
                        await send(
                            {"t": T_DATA, "n": pos, "enc": ENC_TOK},
                            payload_bin,
                        )
                rest = items[pos:]
                if rest:
                    if stats:
                        stats.frames_total += 1
                    if len(rest) == 1:
                        await send({"t": T_DATA}, codec.pack(rest[0]))
                    else:
                        await send({"t": T_DATA, "n": len(rest)}, codec.pack(rest))
            kind, item = terminal
            if kind == _DONE:
                await send({"t": T_DONE})
            else:
                raise item  # handler exception: report like the inline path
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — stream errors go to the caller
            logger.exception("handler error on %s", subject)
            if stats:
                stats.errors_total += 1
            if isinstance(e, DeadlineExceeded):
                # machine-readable: the caller re-raises DeadlineExceeded
                # (not a generic EngineError) so its migration/retry loops
                # STOP instead of burning another worker slot
                ctrl = {
                    "t": T_ERR, "code": ERR_DEADLINE,
                    "error": f"{type(e).__name__}: {e}",
                }
            elif isinstance(e, StreamSevered):
                # deliberate mid-stream sever (role-morph drain): ride the
                # `draining` code so the CALLER raises StreamLost and its
                # migration machinery resumes the session on a peer from
                # the checkpointed tail — a plain T_ERR would surface as a
                # terminal EngineError and kill the stream instead
                ctrl = {
                    "t": T_ERR, "code": ERR_DRAINING,
                    "error": f"{type(e).__name__}: {e}",
                }
            else:
                ctrl = {"t": T_ERR, "error": f"{type(e).__name__}: {e}"}
            try:
                await send(ctrl)
            except (ConnectionError, RuntimeError):
                pass
        finally:
            if pump_task is not None:
                pump_task.cancel()
            if stats:
                stats.requests_active -= 1
            self._active.pop((writer, stream_id), None)


class EngineError(RuntimeError):
    """Terminal error surfaced from a remote engine stream."""


class StreamLost(EngineError):
    """Connection to the worker died mid-stream — the trigger for request
    migration (reference migration.rs)."""


class StreamSevered(EngineError):
    """Raised BY a worker's handler to deliberately cut an in-flight
    stream (role-morph drain: the outgoing role's lanes must move to a
    peer NOW, not when their decodes finish). The server maps it to a
    `draining`-coded T_ERR, which the caller raises as StreamLost — so
    the frontend's migration loop re-routes the session and it resumes
    from its durable checkpoint instead of dying with the role."""


class DeadlineExceeded(EngineError):
    """The context's end-to-end deadline passed. Clean and terminal:
    retry loops (migration, reconnects) must stop, not spin."""


class _Connection:
    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self.write_lock = asyncio.Lock()
        self.streams: Dict[int, asyncio.Queue] = {}
        self.recv_task: Optional[asyncio.Task] = None
        self.closed = False

    async def recv_loop(self):
        try:
            while True:
                frame = await codec.read_frame(self.reader)
                if frame is None:
                    break
                control, payload = frame
                q = self.streams.get(control.get("stream"))
                if q is not None:
                    q.put_nowait((control, payload))
        except ConnectionError:
            pass
        except asyncio.CancelledError:
            raise  # cleanup below still runs; the task records cancelled
        finally:
            self.closed = True
            for q in self.streams.values():
                q.put_nowait(({"t": T_LOST}, b""))
            self.writer.close()


class RequestPlaneClient:
    """Caller side: pooled connections to worker request-plane servers,
    many concurrent streams multiplexed per connection
    (reference AddressedPushRouter addressed_router.rs:52)."""

    def __init__(self, connect_timeout: float = 5.0):
        self._conns: Dict[str, _Connection] = {}
        self._stream_ids = itertools.count(1)
        # zero-copy token path: advertise ENC_TOK decoding on every stream
        # we open (per-client so test clusters can flip the env after
        # import, like the server's coalesce knobs)
        self.binary_tokens = bool(_env("DYN_WIRE_BINARY_TOKENS", True, bool))
        # per-address dial serialization.  Entries are PRUNED when the
        # address's connection dies (recv-loop done-callback below): under
        # worker churn the router dials a new host:port per replacement,
        # and a setdefault-only dict would grow one lock per address ever
        # seen, forever.
        self._conn_locks: Dict[str, asyncio.Lock] = {}
        self.connect_timeout = connect_timeout

    def _evict_conn(self, address: str, conn: _Connection):
        """The connection's recv loop ended: it can never carry another
        stream.  Drop it from the pool (identity-checked — a newer dial
        may already own the slot) and prune the address's dial lock once
        no dial is in flight."""
        if self._conns.get(address) is conn:
            self._conns.pop(address, None)
        lock = self._conn_locks.get(address)
        if lock is not None and not lock.locked() \
                and address not in self._conns:
            self._conn_locks.pop(address, None)

    async def _get_conn(
        self, address: str, deadline: Optional[float] = None
    ) -> _Connection:
        conn = self._conns.get(address)
        if conn is not None and not conn.closed:
            return conn
        lock = self._conn_locks.setdefault(address, asyncio.Lock())
        try:
            return await self._dial_locked(address, lock, deadline)
        except BaseException:
            # no connection materialized (refused/timed out/black-holed):
            # a lock kept for an address we never reached is pure growth
            if address not in self._conns and not lock.locked() \
                    and self._conn_locks.get(address) is lock:
                self._conn_locks.pop(address, None)
            raise

    async def _dial_locked(
        self, address: str, lock: asyncio.Lock, deadline: Optional[float]
    ) -> _Connection:
        async with lock:
            conn = self._conns.get(address)
            if conn is not None and not conn.closed:
                return conn
            host, _, port = address.rpartition(":")
            # a black-holed address (dead host, dropped SYN) must raise
            # StreamLost within the connect budget, never hang the caller;
            # the context deadline tightens the budget further
            timeout = self.connect_timeout
            if deadline is not None:
                timeout = min(timeout, max(0.0, deadline - time.monotonic()))

            async def _dial():
                f = faults.FAULTS
                if f.enabled:
                    act = await f.on("request_plane.connect")
                    if act == "refuse":
                        raise ConnectionRefusedError(
                            f"injected: connect to {address} refused"
                        )
                return await asyncio.open_connection(host, int(port))

            try:
                reader, writer = await asyncio.wait_for(_dial(), timeout)
            except asyncio.TimeoutError:
                raise StreamLost(
                    f"connect to {address} timed out after {timeout:.1f}s"
                ) from None
            tune_transport(writer)
            current = self._conns.get(address)
            if current is not None and not current.closed:
                # a racing dial through a just-pruned lock won: keep ONE
                # connection per address, drop ours unused
                writer.close()
                return current
            conn = _Connection(reader, writer)
            conn.recv_task = asyncio.create_task(conn.recv_loop())
            conn.recv_task.add_done_callback(
                lambda _t, a=address, c=conn: self._evict_conn(a, c)
            )
            self._conns[address] = conn
            return conn

    async def close(self):
        for conn in self._conns.values():
            # unblock consumers parked on queue.get() FIRST: they unwind
            # via the normal StreamLost path instead of hanging on a queue
            # nobody will ever fill again
            conn.closed = True
            for q in conn.streams.values():
                q.put_nowait(({"t": T_LOST}, b""))
            if conn.recv_task:
                conn.recv_task.cancel()
            conn.writer.close()
        self._conns.clear()
        self._conn_locks.clear()

    async def ping(self, address: str, timeout: float = 5.0) -> float:
        """Transport liveness probe: one ping/pong round-trip on the pooled
        connection (no handler dispatch — cheaper than a canary request
        and usable against a draining worker). Returns the RTT in seconds;
        raises StreamLost when the peer is unreachable or silent past
        `timeout`."""
        try:
            # the dial shares the probe's budget, not the default connect
            # timeout — a black-holed host answers within `timeout` too
            conn = await self._get_conn(
                address, deadline=time.monotonic() + timeout
            )
        except OSError as e:
            raise StreamLost(f"cannot connect to {address}: {e}") from e
        stream_id = next(self._stream_ids)
        queue: asyncio.Queue = asyncio.Queue()
        conn.streams[stream_id] = queue
        t0 = time.monotonic()
        try:
            async with conn.write_lock:
                await codec.write_frame(
                    conn.writer, {"t": T_PING, "stream": stream_id}
                )
            try:
                control, _ = await asyncio.wait_for(queue.get(), timeout)
            except asyncio.TimeoutError:
                raise StreamLost(
                    f"ping to {address} timed out after {timeout:.1f}s"
                ) from None
            t = control.get("t")
            if t == T_PONG:
                return time.monotonic() - t0
            raise StreamLost(f"ping to {address} answered '{t}', not pong")
        except (ConnectionError, OSError) as e:
            raise StreamLost(f"ping to {address} failed: {e}") from e
        finally:
            conn.streams.pop(stream_id, None)

    async def call(
        self,
        address: str,
        subject: str,
        request: Any,
        context: Optional[Context] = None,
    ) -> AsyncIterator[Any]:
        """Issue a request; returns the async response stream. Cancelling the
        context sends a cancel frame to the worker."""
        ctx = context or Context()
        if ctx.deadline_exceeded():
            raise DeadlineExceeded(f"deadline passed before calling {address}")
        try:
            conn = await self._get_conn(address, deadline=ctx.deadline)
        except OSError as e:
            raise StreamLost(f"cannot connect to {address}: {e}") from e
        stream_id = next(self._stream_ids)
        queue: asyncio.Queue = asyncio.Queue()
        conn.streams[stream_id] = queue

        control = {"t": T_REQ, "stream": stream_id, "subject": subject, "ctx_id": ctx.id}
        if self.binary_tokens:
            control["bin"] = 1
        remaining = ctx.time_remaining()
        if remaining is not None:
            # ship the REMAINING budget, not an absolute time: monotonic
            # clocks don't compare across hosts
            control["deadline_ms"] = int(remaining * 1000)
        trace = current_trace()
        if trace is not None:
            control["traceparent"] = trace.traceparent()
        try:
            async with conn.write_lock:
                await codec.write_frame(conn.writer, control, codec.pack(request))
        except (ConnectionError, OSError) as e:
            conn.streams.pop(stream_id, None)
            raise StreamLost(f"send to {address} failed: {e}") from e

        return self._stream(conn, stream_id, queue, ctx)

    async def _stream(
        self, conn: _Connection, stream_id: int, queue: asyncio.Queue, ctx: Context
    ) -> AsyncIterator[Any]:
        cancel_sent = False
        kill_task = asyncio.create_task(ctx.killed())
        stop_task = asyncio.create_task(ctx.stopped())
        get_task: Optional[asyncio.Task] = None
        try:
            while True:
                get_task = asyncio.create_task(queue.get())
                waiters = {get_task, kill_task}
                if not cancel_sent:
                    waiters.add(stop_task)
                done, _pending = await asyncio.wait(
                    waiters, return_when=asyncio.FIRST_COMPLETED
                )
                if kill_task in done:
                    await self._send_cancel(conn, stream_id, kill=True)
                    return
                if stop_task in done and not cancel_sent:
                    # graceful stop: tell the worker, then keep draining so the
                    # engine can emit its final (usage) chunk
                    cancel_sent = True
                    await self._send_cancel(conn, stream_id, kill=False)
                if get_task not in done:
                    get_task.cancel()
                    continue
                # the task is in asyncio.wait's done set, so result()
                # returns immediately — it never blocks here
                control, payload = get_task.result()  # dynolint: disable=async-blocking -- task already done
                get_task = None
                t = control.get("t")
                if t == T_DATA:
                    f = faults.FAULTS
                    if f.enabled:
                        act = await f.on("request_plane.frame")
                        if act == "sever":
                            # sever the CONNECTION, not just this stream:
                            # every stream multiplexed on it sees a real
                            # mid-flight loss, exactly like a worker SIGKILL.
                            # Mark it dead NOW so a concurrent _get_conn
                            # never hands out the dying transport in the
                            # window before recv_loop's finally runs
                            conn.closed = True
                            conn.writer.close()
                            raise StreamLost("injected: connection severed mid-stream")
                    enc = control.get("enc")
                    if enc == ENC_TOK:
                        # binary token-delta batch: flat u32 decode into
                        # ONE merged delta — the same concatenation the
                        # frontend's merge_token_deltas would apply to the
                        # frame's items (token counts/order preserved)
                        for it in codec.unpack_token_items(
                            payload, merge=True
                        ):
                            yield it
                    elif enc is not None:
                        raise EngineError(
                            f"unknown payload encoding {enc!r} (worker "
                            "newer than this client?)"
                        )
                    elif control.get("n"):
                        # coalesced multi-item frame: the payload is the
                        # packed item list, committed atomically on the
                        # wire — yield in order
                        for it in codec.unpack(payload):
                            yield it
                    else:
                        yield codec.unpack(payload)
                elif t == T_DONE:
                    return
                elif t == T_ERR:
                    code = control.get("code")
                    if code == ERR_DRAINING:
                        # a draining worker is connection-level unavailable:
                        # routers and migration retry another instance
                        raise StreamLost(control.get("error", "worker draining"))
                    if code == ERR_DEADLINE:
                        # terminal, not retryable: the request's own budget
                        # ran out worker-side
                        raise DeadlineExceeded(
                            control.get("error", "deadline exceeded")
                        )
                    raise EngineError(control.get("error", "engine error"))
                elif t == T_LOST:
                    raise StreamLost("connection to worker lost mid-stream")
        finally:
            for task in (kill_task, stop_task, get_task):
                if task is not None:
                    task.cancel()
            conn.streams.pop(stream_id, None)

    async def _send_cancel(self, conn: _Connection, stream_id: int, kill: bool):
        try:
            async with conn.write_lock:
                await codec.write_frame(
                    conn.writer, {"t": T_CANCEL, "stream": stream_id, "kill": kill}
                )
        except (ConnectionError, OSError):
            pass
