"""dynochaos: deterministic, seeded fault injection for the serving plane.

Dynamo's robustness story — request migration on worker death, canary
health checks, lease-reaped discovery — is only trustworthy if every
failure path is reachable ON DEMAND and proven correct under a seeded
schedule. This module is the single switchboard: named injection points
threaded through the request plane (`request_plane.connect`,
`request_plane.frame`), discovery (`discovery.lease`, `discovery.watch`),
the engines (`engine.step`, `mocker.step`) and the KV data plane
(`kv_transfer.chunk`), each guarded by the pattern

    f = faults.FAULTS
    if f.enabled:
        act = await f.on("point.name")
        ...site-specific handling of `act`...

When no plan is configured, `FAULTS` is the shared `NOOP` pass-through
object (``enabled = False``) installed at import time, so the hot path
pays one attribute load and a falsy branch — behavior is byte-identical
to a build without this module (guarded by a test asserting
``faults.FAULTS is faults.NOOP``).

Configuration (all registered in `runtime/config.py:ENV_REGISTRY`):

  DYN_FAULT_PLAN     the plan string (grammar below); unset -> NOOP
  DYN_FAULT_SEED     RNG seed for probabilistic rules (default 0)
  DYN_FAULT_DISABLE  global kill-switch: force NOOP even with a plan set

Plan grammar — semicolon-separated rules, one per injection point hit
pattern::

    plan  = rule (";" rule)*
    rule  = point [":" spec ("," spec)*]
    spec  = action ["@t=" SECONDS]      e.g.  sever   drop@t=2.0
          | "after=" N                  pass the first N hits, then fire
          | "at=" N                     fire exactly on the Nth hit (1-based)
          | "t=" SECONDS                fire once armed longer than SECONDS
          | "p=" PROB                   fire with seeded probability
          | "times=" N                  fire at most N times (default 1;
                                        p= rules default to unlimited)
          | "delay=" SECONDS            sleep length for the delay action

    Example: request_plane.frame:sever,after=3;discovery.lease:drop@t=2.0

Actions are interpreted by the site: `error` raises :class:`FaultError`
from :meth:`FaultInjector.on`; `delay` sleeps ``delay=`` seconds and
returns; `hang` sleeps effectively forever (the site's timeout must
bound it); everything else (`sever`, `refuse`, `drop`, `partial`, …) is
returned as a string for the site to act on.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

# actions on() resolves itself; all others are returned to the site
_HANG_SECONDS = 3600.0
_UNLIMITED = 1 << 30

#: Canonical injection points: name -> one-line description (actions the
#: site interprets, then where it bites). This table is the source of
#: truth three consumers share: DYN_FAULT_PLAN validation-by-docs, the
#: generated point table in docs/fault_tolerance.md
#: (`python -m dynamo_tpu.analysis --emit-fault-docs`), and the
#: `flow-fault-point-registry` dynolint rule, which fails CI when an
#: injection site names a point missing here (or an entry here has no
#: site left). Sites may use ad-hoc names in tests, but every
#: `faults.FAULTS.on/check(...)` call inside the package must resolve
#: into this table.
KNOWN_FAULT_POINTS = {
    "request_plane.connect":
        "`refuse` | `hang` — client dial of a worker's request plane",
    "request_plane.frame":
        "`sever` | `delay` | `hang` — client recv, per data frame; "
        "`sever` kills the whole connection",
    "discovery.lease":
        "`drop` — lease keepalive tick; simulates server-side TTL expiry",
    "discovery.watch":
        "`disconnect` — discovery recv loop; drops the control-plane "
        "connection to exercise the re-watch path",
    "engine.step":
        "`error` — JaxEngine step loop; fail-all then migration",
    "mocker.step":
        "`error` — MockEngine step loop; fail-all",
    "kv_transfer.chunk":
        "`sever` | `delay` — KV data-plane chunk serve; partial transfer",
    "kv_transfer.pull":
        "`sever` | `delay` — peer-side kvbm block pull (cluster KV "
        "fabric onboard); `sever` drops the connection mid-pull and the "
        "admission path falls back to local-tier/recompute, counted",
    "planner.scrape":
        "`error` | `hang` | `delay` — planner's frontend /metrics scrape; "
        "the planner retries with backoff and ages out stale observations",
    "planner.connector":
        "`error` — planner connector set_replicas; the planner retries "
        "with backoff and re-asserts the target next interval",
    "worker.spawn":
        "`error` | `crash` — LocalProcessConnector replica spawn; `error` "
        "fails the exec, `crash` kills the child before it reports ready",
    "worker.kill":
        "`kill` — LocalProcessConnector reconcile tick: SIGKILL a live "
        "managed replica with NO drain (hard worker death); migration "
        "must absorb the lost streams and reconcile respawns the corpse",
    "worker.morph":
        "`error` | `delay` | `hang` | `crash` — engine role-morph stages "
        "(checked mid-drain and again mid-flip); `error` rolls the worker "
        "back to its original role (drained sessions already resumed on "
        "peers), `crash` tears the worker down mid-morph like a SIGKILL "
        "so lease TTL + migration corpse-handling absorb it",
    "kv_transfer.checkpoint":
        "`sever` | `delay` — session-checkpoint push to the peer's G2 "
        "(kvbm/checkpoint.py); `sever` drops the batch (counted) and "
        "quarantines the peer — serving streams never notice",
    "kvbm.offload":
        "`error` | `delay` — kvbm-tier thread store of one offload batch; "
        "`error` drops the batch (counted), streams never notice",
    "kvbm.onboard":
        "`error` | `delay` — tier load at admission onboard; `error` "
        "falls back to full prefill of that span",
    "lora.onboard":
        "`error` | `delay` — adapter-tier host->device onboard at "
        "admission (models/lora_pool.py); `error` refuses the request "
        "with a typed LoraPoolError (counted), `delay` stretches the "
        "cold adapter switch — either way the stream is rejected or "
        "late, never corrupt",
    "gate.admit":
        "`reject` — frontend admission decision (dynogate); forces a "
        "clean 429-with-Retry-After on the hit, exercising the typed "
        "rejection path before tokenization",
}


class FaultError(RuntimeError):
    """An injected fault (action `error`). Typed so tests and callers can
    tell a chaos-induced failure from an organic one."""


class MorphCrash(FaultError):
    """Injected `worker.morph:crash`: the engine raises this out of its
    morph sequence INSTEAD of rolling back, so the worker harness tears
    the process down mid-morph like a SIGKILL — lease lingers to TTL,
    streams sever, and the PR 15 migration/corpse machinery absorbs it."""


@dataclass
class _Rule:
    point: str
    action: str = "error"
    after: Optional[int] = None
    at: Optional[int] = None
    t: Optional[float] = None
    p: Optional[float] = None
    times: int = 1
    delay: float = 0.05
    # mutable trigger state
    hits: int = 0
    fired: int = 0

    def should_fire(self, elapsed: float, rng: random.Random) -> bool:
        self.hits += 1
        if self.fired >= self.times:
            return False
        if self.after is not None and self.hits <= self.after:
            return False
        if self.at is not None and self.hits != self.at:
            return False
        if self.t is not None and elapsed < self.t:
            return False
        if self.p is not None and rng.random() >= self.p:
            return False
        self.fired += 1
        return True


def parse_plan(plan: str) -> List[_Rule]:
    """Parse a plan string; raises ValueError on malformed rules so a typo
    in DYN_FAULT_PLAN fails loudly at startup, not silently as a no-op."""
    rules: List[_Rule] = []
    for raw in plan.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        point, _, spec = raw.partition(":")
        point = point.strip()
        if not point:
            raise ValueError(f"fault rule missing point name: {raw!r}")
        rule = _Rule(point=point)
        saw_times = False
        for item in filter(None, (s.strip() for s in spec.split(","))):
            if "=" in item and item.split("=", 1)[0] in (
                "after", "at", "t", "p", "times", "delay"
            ):
                key, val = item.split("=", 1)
                try:
                    if key in ("after", "at", "times"):
                        setattr(rule, key, int(val))
                        saw_times = saw_times or key == "times"
                    else:
                        setattr(rule, key, float(val))
                except ValueError as e:
                    raise ValueError(f"bad fault spec {item!r} in {raw!r}") from e
            else:
                # bare action, optionally with @t= sugar: "drop@t=2.0"
                action, _, at_t = item.partition("@t=")
                if "=" in action:
                    # a misspelled key ("atfer=3") must fail loudly, not
                    # silently become a never-matching action
                    raise ValueError(f"unknown fault spec key {item!r} in {raw!r}")
                rule.action = action
                if at_t:
                    try:
                        rule.t = float(at_t)
                    except ValueError as e:
                        raise ValueError(f"bad fault spec {item!r} in {raw!r}") from e
        if rule.p is not None and not saw_times:
            rule.times = _UNLIMITED
        rules.append(rule)
    return rules


class FaultInjector:
    """Compiled fault plan. One instance per process (module-level FAULTS);
    hit counting and the probabilistic RNG are deterministic for a given
    (plan, seed) and hit sequence."""

    enabled = True

    def __init__(self, plan: str, seed: int = 0):
        self.plan = plan
        self.seed = seed
        self._rules: Dict[str, List[_Rule]] = {}
        for rule in parse_plan(plan):
            self._rules.setdefault(rule.point, []).append(rule)
        self._rng = random.Random(seed)
        self._t0 = time.monotonic()
        self.fired_log: List[tuple] = []  # (point, action) in firing order

    def arm(self):
        """Restart the t= clock (configure() calls this)."""
        self._t0 = time.monotonic()

    def check(self, point: str) -> Optional[str]:
        """Count a hit on `point`; return the action to apply, or None.
        Synchronous — for sites that cannot await. EVERY rule on the point
        counts every hit (so at=/after= positions stay exact in multi-rule
        plans); when two rules would fire on the same hit, the first wins
        and the later one keeps its budget for a subsequent hit."""
        rules = self._rules.get(point)
        if not rules:
            return None
        elapsed = time.monotonic() - self._t0
        action = None
        for rule in rules:
            if not rule.should_fire(elapsed, self._rng):
                continue
            if action is None:
                action = rule.action
                self.fired_log.append((point, action))
                logger.warning("dynochaos: firing %s:%s (hit %d)",
                               point, action, rule.hits)
            else:
                rule.fired -= 1  # refund: one action per hit
        return action

    async def on(self, point: str) -> Optional[str]:
        """Count a hit; resolve `error`/`delay`/`hang` actions in place.
        Returns the action name for site-interpreted actions, None if
        nothing fired."""
        act = self.check(point)
        if act is None:
            return None
        if act == "error":
            raise FaultError(f"injected fault at {point}")
        if act == "delay":
            delay = next(
                r.delay for r in self._rules[point] if r.action == "delay"
            )
            await asyncio.sleep(delay)
        elif act == "hang":
            await asyncio.sleep(_HANG_SECONDS)
        return act


class _NoopInjector:
    """Zero-cost pass-through installed when no plan is configured. Sites
    short-circuit on `.enabled` so none of these methods run on the hot
    path; they exist for direct callers."""

    __slots__ = ()
    enabled = False

    def check(self, point: str) -> Optional[str]:
        return None

    async def on(self, point: str) -> Optional[str]:
        return None


NOOP = _NoopInjector()


def _from_env():
    from .config import env_bool

    if env_bool("DYN_FAULT_DISABLE"):
        return NOOP
    plan = os.environ.get("DYN_FAULT_PLAN")
    if not plan:
        return NOOP
    seed = int(os.environ.get("DYN_FAULT_SEED", "0"))
    inj = FaultInjector(plan, seed)
    logger.warning("dynochaos ACTIVE: plan=%r seed=%d", plan, seed)
    return inj


def configure(plan: str, seed: int = 0) -> FaultInjector:
    """Install an active injector (tests / in-proc chaos harnesses)."""
    global FAULTS
    inj = FaultInjector(plan, seed)
    inj.arm()
    FAULTS = inj
    return inj


def reset():
    """Restore the environment-derived injector (NOOP when no plan set)."""
    global FAULTS
    FAULTS = _from_env()


#: The process-wide injector. Import the MODULE and read `faults.FAULTS`
#: at call time (configure()/reset() rebind it); never `from ... import
#: FAULTS`, which would freeze the binding.
FAULTS = _from_env()
