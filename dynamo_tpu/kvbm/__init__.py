"""KVBM — multi-tier KV block manager (TPU rebuild of reference
lib/llm/src/block_manager, 21k LoC Rust: KvBlockManager block_manager.rs:99,
OffloadManager offload.rs, Storage traits storage.rs:157).

Tiers (reference CacheLevel, block_manager.rs:63):
  G1  device HBM      — the engine's paged kv arrays (engine/kv_cache.py)
  G2  host RAM        — preallocated numpy pool (pinned-host analogue)
  G3  local disk      — np.memmap pool file

Where the reference moves blocks with a CUDA kernel (block_copy.cu) + NIXL,
the TPU path is: XLA gather (`extract_pages`) for device->host DMA and
`inject_pages` scatter for host->device, both jitted; see
engine/engine.py. Offload is write-through at block-commit time so G1
eviction never needs a device read-back.
"""

from .storage import DiskTier, HostTier
from .manager import KvbmConfig, KvBlockManager, KvbmConnector
from .distributed import KvbmDistributed

__all__ = [
    "DiskTier",
    "HostTier",
    "KvbmConfig",
    "KvBlockManager",
    "KvbmConnector",
    "KvbmDistributed",
]
