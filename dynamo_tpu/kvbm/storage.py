"""KVBM storage tiers: host-RAM and disk block pools.

Reference: lib/llm/src/block_manager/storage.rs (Storage traits :157,219,322)
and layout.rs (fully-contiguous layout). Each tier is a fixed-capacity pool
of KV blocks keyed by the chained block hash (llm/tokens.py — the SAME hash
the router indexes), with a pluggable eviction policy (every block in a
tier is an unreferenced cache copy; onboarding copies data out, so no
pinning is needed).

Eviction policies (DYN_KVBM_EVICTION, docs/kvbm.md):

  ``lru``           evict the least-recently-touched block (the seed
                    behavior; `get` and re-`put` both count as touches).
  ``lfu``           evict the least-frequently-touched block, oldest
                    touch breaking ties (lazy-heap implementation: stale
                    heap entries are skipped at eviction time, so touches
                    stay O(log n) and eviction is amortized O(log n)).
  ``prefix-aware``  LRU, but a block with a live DESCENDANT in the same
                    pool is protected: because hashes are chained, an
                    interior block is useful exactly as long as a deeper
                    block extends it — evicting the interior block first
                    would break the child's onboardable prefix while its
                    bytes still occupy a slot (the RTP-LLM / Mooncake
                    leaf-first heuristic). A chained forest always has a
                    leaf, so the scan terminates; blocks with unknown
                    parentage (warm disk restart) just look like roots.

A block is one page of one sequence across all layers:
    k, v: [num_layers, page_size, num_kv_heads, head_dim]
"""

from __future__ import annotations

import heapq
import json
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

EVICTION_POLICIES = ("lru", "lfu", "prefix-aware")


class _BlockPool:
    """Shared slot-pool + eviction bookkeeping for both tiers. Subclasses
    supply the backing arrays (`_k`/`_v`) and may pre-seed `_by_hash`
    before calling `_init_pool`."""

    name = "pool"

    def __init__(self, capacity: int, block_shape: tuple, dtype,
                 policy: str = "lru"):
        if policy not in EVICTION_POLICIES:
            raise ValueError(
                f"unknown eviction policy {policy!r}; want one of "
                f"{'/'.join(EVICTION_POLICIES)}"
            )
        self.capacity = capacity
        self.block_shape = tuple(block_shape)
        self.dtype = np.dtype(dtype)
        self.policy = policy
        self._by_hash: Dict[int, int] = {}  # seq_hash -> slot
        self._k: np.ndarray
        self._v: np.ndarray
        self._free: List[int] = []
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        # lfu bookkeeping (lazy heap: entries go stale when a hash is
        # touched again or evicted; victim search pops until fresh)
        self._freq: Dict[int, int] = {}
        self._heap: List[Tuple[int, int, int]] = []  # (freq, tick, hash)
        self._tick = 0
        # prefix-aware bookkeeping: parent link + in-pool children per
        # hash, plus the childless blocks in recency order so victim
        # selection is O(1), not an LRU scan under the manager lock
        self._parent: Dict[int, int] = {}  # child hash -> parent hash
        self._children: Dict[int, Set[int]] = {}  # parent -> in-pool children
        self._leaves: "OrderedDict[int, None]" = OrderedDict()
        # counters
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _init_pool(self):
        """Build free list / recency from whatever `_by_hash` holds (empty
        for a cold start; the persisted index for a warm disk restart —
        restored blocks carry no parent links, so prefix-aware treats them
        as roots)."""
        used = set(self._by_hash.values())
        self._free = [s for s in range(self.capacity - 1, -1, -1) if s not in used]
        self._lru = OrderedDict((h, None) for h in self._by_hash)
        self._freq = {h: 1 for h in self._by_hash}
        self._heap = []
        for h in self._by_hash:
            self._push_heap(h)
        self._parent = {}
        self._children = {}
        self._leaves = OrderedDict((h, None) for h in self._by_hash)

    def _push_heap(self, seq_hash: int):
        self._tick += 1
        heapq.heappush(self._heap, (self._freq[seq_hash], self._tick, seq_hash))
        if len(self._heap) > max(4 * self.capacity, 64):
            # lazy-heap compaction: every touch pushes an entry but only
            # eviction pops, so a hit-heavy tier whose working set fits
            # in capacity would otherwise grow the heap without bound.
            # freq only increases, so exactly one entry per live hash
            # matches its current freq — keep those, drop the stale.
            self._heap = [
                (f, t, h) for f, t, h in self._heap
                if h in self._by_hash and self._freq.get(h) == f
            ]
            heapq.heapify(self._heap)

    def _touch(self, seq_hash: int):
        self._lru[seq_hash] = None
        self._lru.move_to_end(seq_hash)
        if seq_hash in self._leaves:
            self._leaves.move_to_end(seq_hash)
        if self.policy == "lfu":
            self._freq[seq_hash] = self._freq.get(seq_hash, 0) + 1
            self._push_heap(seq_hash)

    def _pick_victim(self) -> int:
        if self.policy == "lfu":
            while self._heap:
                freq, _, h = heapq.heappop(self._heap)
                if h in self._by_hash and self._freq.get(h) == freq:
                    return h
            return next(iter(self._lru))  # heap drifted (shouldn't happen)
        if self.policy == "prefix-aware":
            if self._leaves:
                return next(iter(self._leaves))
            # every block has an in-pool descendant — impossible for a
            # chained forest, but stale bookkeeping must not wedge the pool
            return next(iter(self._lru))
        return next(iter(self._lru))

    def _forget(self, seq_hash: int):
        """Drop all policy bookkeeping for an evicted hash."""
        self._lru.pop(seq_hash, None)
        self._leaves.pop(seq_hash, None)
        self._freq.pop(seq_hash, None)
        parent = self._parent.pop(seq_hash, None)
        if parent is not None:
            kids = self._children.get(parent)
            if kids is not None:
                kids.discard(seq_hash)
                if not kids:
                    del self._children[parent]
                    if parent in self._by_hash:
                        # last in-pool child left: the parent is a leaf
                        # again, at the MRU end (it had descendants — it
                        # earned its keep)
                        self._leaves[parent] = None
        # children keep their _parent link: if this hash is re-stored the
        # chain is intact; _children[seq_hash] stays until its kids leave

    def __len__(self) -> int:
        return len(self._by_hash)

    def has(self, seq_hash: int) -> bool:
        return seq_hash in self._by_hash

    def put(
        self, seq_hash: int, k: np.ndarray, v: np.ndarray,
        parent: Optional[int] = None,
    ) -> Optional[Tuple[int, Optional[np.ndarray], Optional[np.ndarray], Optional[int]]]:
        """Store a block. If the pool was full, returns the evicted
        (hash, k, v, parent) — with data copies only when
        `evict_with_data` — so the caller can cascade it (parent included)
        to the next tier. Returns None otherwise. `parent` is the
        preceding block hash in the chain when known (prefix-aware
        protection)."""
        if seq_hash in self._by_hash:
            self._touch(seq_hash)
            if parent is not None and seq_hash not in self._parent:
                self._link_parent(seq_hash, parent)
            return None
        evicted = None
        if not self._free:
            old_hash = self._pick_victim()
            slot = self._by_hash.pop(old_hash)
            old_parent = self._parent.get(old_hash)
            if self.evict_with_data:
                evicted = (old_hash, self._k[slot].copy(), self._v[slot].copy(),
                           old_parent)
            else:
                evicted = (old_hash, None, None, old_parent)
            self._forget(old_hash)
            self.evictions += 1
            self._free.append(slot)
        slot = self._free.pop()
        self._k[slot] = k
        self._v[slot] = v
        self._by_hash[seq_hash] = slot
        self._lru[seq_hash] = None
        if not self._children.get(seq_hash):
            # childless on arrival (a re-added interior block whose kids
            # are still pooled stays protected)
            self._leaves[seq_hash] = None
        if self.policy == "lfu":
            self._freq[seq_hash] = 1
            self._push_heap(seq_hash)
        if parent is not None:
            self._link_parent(seq_hash, parent)
        return evicted

    def _link_parent(self, seq_hash: int, parent: int):
        self._parent[seq_hash] = parent
        self._children.setdefault(parent, set()).add(seq_hash)
        self._leaves.pop(parent, None)  # parent now interior

    def get(self, seq_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Returns VIEWS into the pool; callers that hold the result across
        further put()s must copy."""
        slot = self._by_hash.get(seq_hash)
        if slot is None:
            self.misses += 1
            return None
        self.hits += 1
        self._touch(seq_hash)
        return self._k[slot], self._v[slot]

    def clear(self) -> int:
        """Drop every block (admin clear-kv-blocks); slots return to the
        free list, data stays in place until overwritten."""
        n = len(self._by_hash)
        self._by_hash.clear()
        self._init_pool()
        return n

    def stats(self) -> dict:
        return {
            f"{self.name}_blocks": len(self._by_hash),
            f"{self.name}_capacity": self.capacity,
            f"{self.name}_hits": self.hits,
            f"{self.name}_misses": self.misses,
            f"{self.name}_evictions": self.evictions,
        }

    evict_with_data = True


class HostTier(_BlockPool):
    """G2: preallocated host-RAM block pool (pinned-host analogue of
    block_manager/storage/cuda.rs PinnedStorage). Evictions carry the block
    data so the manager can cascade them to disk."""

    name = "host"
    evict_with_data = True

    def __init__(self, capacity: int, block_shape: tuple, dtype,
                 policy: str = "lru"):
        super().__init__(capacity, block_shape, dtype, policy)
        self._k = np.zeros((capacity, *self.block_shape), self.dtype)
        self._v = np.zeros((capacity, *self.block_shape), self.dtype)
        self._init_pool()


class DiskTier(_BlockPool):
    """G3: np.memmap-backed block pool (block_manager/storage/disk.rs).

    Two pool files (k.bin / v.bin) with fixed block slots — the reference's
    fully-contiguous layout (layout.rs). The hash index is persisted to
    index.json by flush() (the engine calls it on close) and loaded on init
    when the pool files validate, so a restarted worker reuses warm blocks
    (reference: G3 tiers persist KV for reuse, offload.rs). Disk is the last
    tier: evictions drop the block, so they carry no data.
    """

    name = "disk"
    evict_with_data = False

    def __init__(self, capacity: int, block_shape: tuple, dtype, path: str,
                 policy: str = "lru"):
        super().__init__(capacity, block_shape, dtype, policy)
        self.path = path
        os.makedirs(path, exist_ok=True)
        shape = (capacity, *self.block_shape)
        index_path = os.path.join(path, "index.json")
        k_path = os.path.join(path, "k.bin")
        v_path = os.path.join(path, "v.bin")
        expected_bytes = int(np.prod(shape)) * self.dtype.itemsize
        mode = "w+"
        if (
            os.path.exists(index_path)
            and os.path.exists(k_path)
            and os.path.exists(v_path)
        ):
            try:
                with open(index_path) as f:
                    saved = json.load(f)
                if (
                    tuple(saved.get("block_shape", ())) == self.block_shape
                    and os.path.getsize(k_path) == expected_bytes
                    and os.path.getsize(v_path) == expected_bytes
                ):
                    self._by_hash = {
                        int(h): s
                        for h, s in saved["index"].items()
                        if 0 <= s < capacity
                    }
                    mode = "r+"  # warm restart: reuse persisted blocks
            except (ValueError, KeyError, OSError):
                self._by_hash = {}
        self._k = np.memmap(k_path, dtype=self.dtype, mode=mode, shape=shape)
        self._v = np.memmap(v_path, dtype=self.dtype, mode=mode, shape=shape)
        self._init_pool()

    def put(self, seq_hash: int, k: np.ndarray, v: np.ndarray,
            parent: Optional[int] = None) -> Optional[int]:
        """Returns the dropped hash if the pool was full, else None."""
        evicted = super().put(seq_hash, k, v, parent=parent)
        return evicted[0] if evicted is not None else None

    def flush(self):
        """Persist pool + index. Crash-consistent: the index is written to
        a temp file and atomically renamed over index.json, so a crash
        mid-flush leaves the PREVIOUS index intact (a torn index.json
        would poison every warm restart until manually deleted). NOT
        thread-safe on its own — call via KvBlockManager.flush(), which
        holds the manager lock."""
        self._k.flush()
        self._v.flush()
        index = {str(h): s for h, s in self._by_hash.items()}
        index_path = os.path.join(self.path, "index.json")
        tmp_path = index_path + ".tmp"
        with open(tmp_path, "w") as f:
            json.dump({"block_shape": self.block_shape, "index": index}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp_path, index_path)
