"""KVBM storage tiers: host-RAM and disk block pools.

Reference: lib/llm/src/block_manager/storage.rs (Storage traits :157,219,322)
and layout.rs (fully-contiguous layout). Each tier is a fixed-capacity pool
of KV blocks keyed by the chained block hash (llm/tokens.py — the SAME hash
the router indexes), with LRU eviction of the whole pool (every block in a
tier is an unreferenced cache copy; onboarding copies data out, so no
pinning is needed).

A block is one page of one sequence across all layers:
    k, v: [num_layers, page_size, num_kv_heads, head_dim]
"""

from __future__ import annotations

import json
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np


class _BlockPool:
    """Shared slot-pool + LRU bookkeeping for both tiers. Subclasses supply
    the backing arrays (`_k`/`_v`) and may pre-seed `_by_hash` before
    calling `_init_pool`."""

    name = "pool"

    def __init__(self, capacity: int, block_shape: tuple, dtype):
        self.capacity = capacity
        self.block_shape = tuple(block_shape)
        self.dtype = np.dtype(dtype)
        self._by_hash: Dict[int, int] = {}  # seq_hash -> slot
        self._k: np.ndarray
        self._v: np.ndarray
        self._free: List[int] = []
        self._lru: "OrderedDict[int, None]" = OrderedDict()

    def _init_pool(self):
        """Build free list / LRU from whatever `_by_hash` holds (empty for a
        cold start; the persisted index for a warm disk restart)."""
        used = set(self._by_hash.values())
        self._free = [s for s in range(self.capacity - 1, -1, -1) if s not in used]
        self._lru = OrderedDict((h, None) for h in self._by_hash)

    def __len__(self) -> int:
        return len(self._by_hash)

    def has(self, seq_hash: int) -> bool:
        return seq_hash in self._by_hash

    def put(
        self, seq_hash: int, k: np.ndarray, v: np.ndarray
    ) -> Optional[Tuple[int, Optional[np.ndarray], Optional[np.ndarray]]]:
        """Store a block. If the pool was full, returns the evicted
        (hash, k, v) — with data copies only when `evict_with_data` — so the
        caller can cascade it to the next tier. Returns None otherwise."""
        if seq_hash in self._by_hash:
            self._lru[seq_hash] = None
            self._lru.move_to_end(seq_hash)
            return None
        evicted = None
        if not self._free:
            old_hash, _ = self._lru.popitem(last=False)
            slot = self._by_hash.pop(old_hash)
            if self.evict_with_data:
                evicted = (old_hash, self._k[slot].copy(), self._v[slot].copy())
            else:
                evicted = (old_hash, None, None)
            self._free.append(slot)
        slot = self._free.pop()
        self._k[slot] = k
        self._v[slot] = v
        self._by_hash[seq_hash] = slot
        self._lru[seq_hash] = None
        return evicted

    def get(self, seq_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Returns VIEWS into the pool; callers that hold the result across
        further put()s must copy."""
        slot = self._by_hash.get(seq_hash)
        if slot is None:
            return None
        self._lru.move_to_end(seq_hash)
        return self._k[slot], self._v[slot]

    def clear(self) -> int:
        """Drop every block (admin clear-kv-blocks); slots return to the
        free list, data stays in place until overwritten."""
        n = len(self._by_hash)
        self._by_hash.clear()
        self._init_pool()
        return n

    def stats(self) -> dict:
        return {
            f"{self.name}_blocks": len(self._by_hash),
            f"{self.name}_capacity": self.capacity,
        }

    evict_with_data = True


class HostTier(_BlockPool):
    """G2: preallocated host-RAM block pool (pinned-host analogue of
    block_manager/storage/cuda.rs PinnedStorage). Evictions carry the block
    data so the manager can cascade them to disk."""

    name = "host"
    evict_with_data = True

    def __init__(self, capacity: int, block_shape: tuple, dtype):
        super().__init__(capacity, block_shape, dtype)
        self._k = np.zeros((capacity, *self.block_shape), self.dtype)
        self._v = np.zeros((capacity, *self.block_shape), self.dtype)
        self._init_pool()


class DiskTier(_BlockPool):
    """G3: np.memmap-backed block pool (block_manager/storage/disk.rs).

    Two pool files (k.bin / v.bin) with fixed block slots — the reference's
    fully-contiguous layout (layout.rs). The hash index is persisted to
    index.json by flush() (the engine calls it on close) and loaded on init
    when the pool files validate, so a restarted worker reuses warm blocks
    (reference: G3 tiers persist KV for reuse, offload.rs). Disk is the last
    tier: evictions drop the block, so they carry no data.
    """

    name = "disk"
    evict_with_data = False

    def __init__(self, capacity: int, block_shape: tuple, dtype, path: str):
        super().__init__(capacity, block_shape, dtype)
        self.path = path
        os.makedirs(path, exist_ok=True)
        shape = (capacity, *self.block_shape)
        index_path = os.path.join(path, "index.json")
        k_path = os.path.join(path, "k.bin")
        v_path = os.path.join(path, "v.bin")
        expected_bytes = int(np.prod(shape)) * self.dtype.itemsize
        mode = "w+"
        if (
            os.path.exists(index_path)
            and os.path.exists(k_path)
            and os.path.exists(v_path)
        ):
            try:
                with open(index_path) as f:
                    saved = json.load(f)
                if (
                    tuple(saved.get("block_shape", ())) == self.block_shape
                    and os.path.getsize(k_path) == expected_bytes
                    and os.path.getsize(v_path) == expected_bytes
                ):
                    self._by_hash = {
                        int(h): s
                        for h, s in saved["index"].items()
                        if 0 <= s < capacity
                    }
                    mode = "r+"  # warm restart: reuse persisted blocks
            except (ValueError, KeyError, OSError):
                self._by_hash = {}
        self._k = np.memmap(k_path, dtype=self.dtype, mode=mode, shape=shape)
        self._v = np.memmap(v_path, dtype=self.dtype, mode=mode, shape=shape)
        self._init_pool()

    def put(self, seq_hash: int, k: np.ndarray, v: np.ndarray) -> Optional[int]:
        """Returns the dropped hash if the pool was full, else None."""
        evicted = super().put(seq_hash, k, v)
        return evicted[0] if evicted is not None else None

    def flush(self):
        """Persist pool + index. NOT thread-safe on its own — call via
        KvBlockManager.flush(), which holds the manager lock."""
        self._k.flush()
        self._v.flush()
        index = {str(h): s for h, s in self._by_hash.items()}
        with open(os.path.join(self.path, "index.json"), "w") as f:
            json.dump({"block_shape": self.block_shape, "index": index}, f)
