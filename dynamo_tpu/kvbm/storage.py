"""KVBM storage tiers: host-RAM and disk block pools.

Reference: lib/llm/src/block_manager/storage.rs (Storage traits :157,219,322)
and layout.rs (fully-contiguous layout). Each tier is a fixed-capacity pool
of KV blocks keyed by the chained block hash (llm/tokens.py — the SAME hash
the router indexes), with LRU eviction of the whole pool (every block in a
tier is an unreferenced cache copy; onboarding copies data out, so no
pinning is needed).

A block is one page of one sequence across all layers:
    k, v: [num_layers, page_size, num_kv_heads, head_dim]
"""

from __future__ import annotations

import json
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np


class HostTier:
    """G2: preallocated host-RAM block pool (pinned-host analogue of
    block_manager/storage/cuda.rs PinnedStorage)."""

    name = "host"

    def __init__(self, capacity: int, block_shape: tuple, dtype):
        self.capacity = capacity
        self.block_shape = tuple(block_shape)
        self.dtype = dtype
        self._k = np.zeros((capacity, *self.block_shape), dtype)
        self._v = np.zeros((capacity, *self.block_shape), dtype)
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._by_hash: Dict[int, int] = {}  # seq_hash -> slot
        self._lru: "OrderedDict[int, None]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._by_hash)

    def has(self, seq_hash: int) -> bool:
        return seq_hash in self._by_hash

    def put(
        self, seq_hash: int, k: np.ndarray, v: np.ndarray
    ) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        """Store a block. Returns the evicted (hash, k, v) if the pool was
        full (caller cascades it to the next tier), else None."""
        if seq_hash in self._by_hash:
            self._lru[seq_hash] = None
            self._lru.move_to_end(seq_hash)
            return None
        evicted = None
        if not self._free:
            old_hash, _ = self._lru.popitem(last=False)
            slot = self._by_hash.pop(old_hash)
            evicted = (old_hash, self._k[slot].copy(), self._v[slot].copy())
            self._free.append(slot)
        slot = self._free.pop()
        self._k[slot] = k
        self._v[slot] = v
        self._by_hash[seq_hash] = slot
        self._lru[seq_hash] = None
        return evicted

    def get(self, seq_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        slot = self._by_hash.get(seq_hash)
        if slot is None:
            return None
        self._lru.move_to_end(seq_hash)
        return self._k[slot], self._v[slot]

    def stats(self) -> dict:
        return {"host_blocks": len(self._by_hash), "host_capacity": self.capacity}


class DiskTier:
    """G3: np.memmap-backed block pool (block_manager/storage/disk.rs).

    Two pool files (k.bin / v.bin) with fixed block slots — the reference's
    fully-contiguous layout (layout.rs). The hash index lives in memory and
    is persisted to index.json on flush() so a restarted worker can reuse
    warm blocks (reference: G3 tiers persist KV for reuse, offload.rs).
    """

    name = "disk"

    def __init__(self, capacity: int, block_shape: tuple, dtype, path: str):
        self.capacity = capacity
        self.block_shape = tuple(block_shape)
        self.dtype = np.dtype(dtype)
        self.path = path
        os.makedirs(path, exist_ok=True)
        shape = (capacity, *self.block_shape)
        self._by_hash: Dict[int, int] = {}
        index_path = os.path.join(path, "index.json")
        k_path = os.path.join(path, "k.bin")
        mode = "w+"
        if os.path.exists(index_path) and os.path.exists(k_path):
            try:
                with open(index_path) as f:
                    saved = json.load(f)
                expected_bytes = int(np.prod(shape)) * self.dtype.itemsize
                if (
                    tuple(saved.get("block_shape", ())) == self.block_shape
                    and os.path.getsize(k_path) == expected_bytes
                ):
                    self._by_hash = {
                        int(h): s
                        for h, s in saved["index"].items()
                        if 0 <= s < capacity
                    }
                    mode = "r+"  # warm restart: reuse persisted blocks
            except (ValueError, KeyError, OSError):
                self._by_hash = {}
        self._k = np.memmap(k_path, dtype=self.dtype, mode=mode, shape=shape)
        self._v = np.memmap(
            os.path.join(path, "v.bin"), dtype=self.dtype, mode=mode, shape=shape
        )
        used = set(self._by_hash.values())
        self._free: List[int] = [s for s in range(capacity - 1, -1, -1) if s not in used]
        self._lru: "OrderedDict[int, None]" = OrderedDict(
            (h, None) for h in self._by_hash
        )

    def __len__(self) -> int:
        return len(self._by_hash)

    def has(self, seq_hash: int) -> bool:
        return seq_hash in self._by_hash

    def put(self, seq_hash: int, k: np.ndarray, v: np.ndarray) -> Optional[int]:
        """Store a block; disk is the last tier, so a full pool drops the
        LRU block entirely. Returns the dropped hash, if any."""
        if seq_hash in self._by_hash:
            self._lru[seq_hash] = None
            self._lru.move_to_end(seq_hash)
            return None
        dropped = None
        if not self._free:
            old_hash, _ = self._lru.popitem(last=False)
            self._free.append(self._by_hash.pop(old_hash))
            dropped = old_hash
        slot = self._free.pop()
        self._k[slot] = k
        self._v[slot] = v
        self._by_hash[seq_hash] = slot
        self._lru[seq_hash] = None
        return dropped

    def get(self, seq_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        slot = self._by_hash.get(seq_hash)
        if slot is None:
            return None
        self._lru.move_to_end(seq_hash)
        return np.asarray(self._k[slot]), np.asarray(self._v[slot])

    def flush(self):
        self._k.flush()
        self._v.flush()
        index = {str(h): s for h, s in self._by_hash.items()}
        with open(os.path.join(self.path, "index.json"), "w") as f:
            json.dump({"block_shape": self.block_shape, "index": index}, f)

    def stats(self) -> dict:
        return {"disk_blocks": len(self._by_hash), "disk_capacity": self.capacity}
