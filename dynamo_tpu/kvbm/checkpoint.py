"""Session KV checkpointing: replicate committed blocks to a peer's G2.

Durable decode sessions (docs/fault_tolerance.md "Request migration"):
with incremental commit, a live session's KV blocks flow into the local
tiers as decode fills pages. This module pushes those blocks on to a
PEER worker's host tier over the existing KV data plane, so a SIGKILL
loses at most the un-checkpointed tail — the survivor onboards the
replicated prefix through the normal three-arm onboard budget instead of
recomputing the whole prefill.

Discipline mirrors the offload pipeline exactly (docs/kvbm.md):

  * the stage is a BOUNDED queue (`DYN_KV_CHECKPOINT` = max staged
    blocks) that refuses the NEWEST block on overflow; a slow/absent
    peer can never stall the step loop or the kvbm-tier thread — a
    dropped block is a lost future resume speedup, never lost
    correctness. Newest-dropped (not oldest): a resume only uses a
    CONTIGUOUS replicated prefix, so dropping the front would turn
    every later-pushed block into dead weight, while refusing the tail
    bounds the loss to exactly what a death loses anyway;
  * block bytes are read from the local tiers with `read_blocks` (no
    promotion, no stat distortion) and pushed with the same `kv_format`
    handshake the peer-pull path uses — a mixed-precision fleet refuses
    typed before any byte moves;
  * push failures quarantine the peer (the mesh's `note_peer_failure`)
    and the batch is dropped + counted; the next batch picks the next
    ready peer.

`DYN_KV_CHECKPOINT=off` (the default) creates none of this — the store
path checks one attribute and the behavior is byte-identical to a build
without checkpointing.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

logger = logging.getLogger(__name__)

# blocks per push batch: big enough to amortize the RTT, small enough
# that one batch never pins the event loop serializing megabytes. The
# effective batch is further capped by BYTES (half the server's
# CHECKPOINT_MAX_PAYLOAD) — a large-KV config (long-context many-layer
# models run ~10MiB/block) would otherwise build count-full batches no
# server accepts, and every push would fail forever
_PUSH_BATCH = 64


def checkpoint_queue_blocks(raw: Optional[str] = None) -> int:
    """Parse DYN_KV_CHECKPOINT: 'off'/''/'0' -> 0 (disabled), an integer
    N -> stage at most N blocks. A typo disables with a warning (a
    checkpoint misconfig must not take the worker down)."""
    raw = raw if raw is not None else os.environ.get("DYN_KV_CHECKPOINT")
    if not raw:
        return 0
    raw = raw.strip().lower()
    if raw in ("off", "0", "false", "no"):
        return 0
    try:
        return max(int(raw), 0)
    except ValueError:
        logger.warning("DYN_KV_CHECKPOINT=%r unknown; checkpointing off", raw)
        return 0


class KvCheckpointer:
    """Bounded replication stage between the local tiers and a peer's G2.

    Staged entries arrive from the kvbm-tier thread
    (`stage_threadsafe`); the consumer task runs on the event loop,
    draining batches, reading block bytes read-only, and pushing them
    over the data plane. All queue state is event-loop-confined —
    `stage_threadsafe` hops through `call_soon_threadsafe`, and the
    consumer pops its batch synchronously before any await.
    """

    def __init__(self, distributed, max_blocks: int):
        self.dist = distributed
        self.max_blocks = max(int(max_blocks), 1)
        self._queue: Deque[Tuple[int, Optional[int]]] = deque()
        # hashes dropped anywhere on the path (stage overflow, no ready
        # peer, failed read/push): any later block whose chain parent was
        # dropped is refused too, so a transient stall can't leave a
        # mid-prefix hole with pushed-but-unreachable bytes behind it.
        # Entries EXPIRE (h -> monotonic deadline): the poison is a
        # bandwidth heuristic — an expired entry risks pushing behind a
        # stale hole (wasted bytes, never wrong bytes; the survivor's
        # admission probes the mesh per block anyway), while permanent
        # poison would let one overflow burst on a popular shared prefix
        # decay replication for the rest of the process's life
        self._refused: dict = {}
        self._refused_ttl_s = 120.0
        self._wake = asyncio.Event()
        self._closed = False
        self._oversize_logged = False
        # counters (stats() snapshots; single event-loop writer)
        self.blocks_staged = 0
        self.blocks_pushed = 0
        self.bytes_pushed = 0
        self.blocks_dropped = 0
        self.push_failures = 0
        self.format_refusals = 0
        self.last_peer: Optional[int] = None

    # -- staging (any thread) ------------------------------------------- #

    def stage_threadsafe(self, hashes, parents):
        loop = self.dist._loop
        if loop is None or self._closed:
            return
        try:
            loop.call_soon_threadsafe(
                self._stage, [int(h) for h in hashes], list(parents)
            )
        except RuntimeError:
            # event loop already closed (teardown race with a late tier-
            # thread store): the replica copy is simply lost, like any
            # other drop — never take the tier thread down with it
            pass

    def _stage(self, hashes: List[int], parents: List[Optional[int]]):
        if self._closed:
            return
        for h, p in zip(hashes, parents):
            # overflow refuses the NEWEST block (blocks stage exactly
            # once, when their page fills): a hole at the FRONT of a
            # session's replicated prefix would make every later block
            # useless for resume — the survivor's prefix match stops at
            # the hole — while losing the tail costs only the tail.
            # A dropped block poisons its descendant chain (bounded TTL):
            # after a transient stall drains, staging a post-hole block
            # would push bytes a contiguous resume can never reach
            if len(self._queue) >= self.max_blocks or self._poisoned(p):
                self.blocks_dropped += 1
                self._poison([h])
                continue
            # a re-offered block repairs its own hole (re-commit after
            # device-cache churn): it is about to be pushed for real
            self._refused.pop(h, None)
            self._queue.append((h, p))
            self.blocks_staged += 1
        self._wake.set()

    def _poisoned(self, h) -> bool:
        if h is None:
            return False
        dl = self._refused.get(h)
        if dl is None:
            return False
        if time.monotonic() >= dl:
            del self._refused[h]
            return False
        return True

    def _poison(self, hashes):
        now = time.monotonic()
        if len(self._refused) >= 4 * self.max_blocks:
            # bounded: purge expired first, then shed oldest-deadline —
            # degrading to a possible stale-hole push, never unbounded
            self._refused = {
                k: v for k, v in self._refused.items() if v > now
            }
            while len(self._refused) >= 4 * self.max_blocks:
                self._refused.pop(min(self._refused, key=self._refused.get))
        dl = now + self._refused_ttl_s
        for h in hashes:
            self._refused[int(h)] = dl

    # -- consumer (event loop task) ------------------------------------- #

    async def run(self):
        while not self._closed:
            # the whole iteration is guarded: an unexpected error (a
            # teardown race in the executor, memory pressure mid-copy)
            # must drop a batch, never kill the replication task —
            # a silently-dead checkpointer would freeze the kvbm_ckpt_*
            # counters while operators believe sessions are durable
            try:
                await self._run_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — replication is best-effort
                logger.exception("kv checkpoint iteration failed; continuing")
                await asyncio.sleep(1.0)

    async def _run_once(self):
        from ..llm.kv_transfer import (
            CHECKPOINT_MAX_PAYLOAD, KvFormatError, push_checkpoint_blocks,
        )
        from ..runtime import faults

        if not self._queue:
            self._wake.clear()
            await self._wake.wait()
            return
        # byte-capped batch: block_nbytes is the k+v payload per block,
        # so this stays under the server's cap with 2x headroom
        per_block = max(int(self.dist.manager.block_nbytes), 1)
        if per_block > CHECKPOINT_MAX_PAYLOAD:
            # a single block no server accepts: replication is
            # impossible for this config — shed staged work instead of
            # dialing a push whose torn connection would read as a dead
            # peer and smear the healthy receiver's quarantine state
            if not self._oversize_logged:
                self._oversize_logged = True
                logger.warning(
                    "kv checkpoint disabled: block_nbytes %d exceeds the "
                    "data-plane payload cap %d",
                    per_block, CHECKPOINT_MAX_PAYLOAD,
                )
            self.blocks_dropped += len(self._queue)
            self._poison([h for h, _ in self._queue])
            self._queue.clear()
            return
        max_batch = max(
            1, min(_PUSH_BATCH, (CHECKPOINT_MAX_PAYLOAD // 2) // per_block)
        )
        batch: List[Tuple[int, Optional[int]]] = []
        while self._queue and len(batch) < max_batch:
            batch.append(self._queue.popleft())
        peer = self.dist.checkpoint_peer()
        if peer is None:
            # no ready peer (single-worker fleet, everyone
            # quarantined): drop + poison — staging forever would just
            # turn the bound into a stall when the fleet grows, and
            # un-poisoned drops would let later chain blocks push
            # behind the hole
            self.blocks_dropped += len(batch)
            self._poison([h for h, _ in batch])
            return
        inst, addr = peer
        self.last_peer = inst
        hashes = [h for h, _ in batch]
        parents = {h: p for h, p in batch}
        # executor hop: read_blocks holds the manager lock while it
        # memcpys up to a full batch of block bytes — inline it would
        # stall the event loop (token emission, admission) and the
        # tier thread's stores, same rule as the serve-side tier reads
        try:
            present, k, v = await asyncio.get_running_loop().run_in_executor(
                None, self.dist.manager.read_blocks, hashes
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — the peer is blameless here
            self.blocks_dropped += len(hashes)
            self._poison(hashes)
            logger.exception("kv checkpoint read failed; batch dropped")
            return
        missing = set(hashes) - set(present)
        if missing:
            self.blocks_dropped += len(missing)
            self._poison(missing)
            # a descendant of a read-time hole (parent evicted between
            # stage and read) is unreachable for a contiguous resume —
            # the same chain rule _stage applies; drop it here rather
            # than pay the data plane and a peer-G2 slot for dead bytes
            dead = set(missing)
            for h in present:  # staged FIFO: parents precede children
                if parents.get(h) in dead:
                    dead.add(h)
            stranded = [h for h in present if h in dead]
            if stranded:
                self.blocks_dropped += len(stranded)
                self._poison(stranded)
                idx = [i for i, h in enumerate(present) if h not in dead]
                present = [present[i] for i in idx]
                k, v = k[idx], v[idx]
        if not present:
            return
        try:
            f = faults.FAULTS
            if f.enabled and await f.on("kv_transfer.checkpoint") == "sever":
                raise ConnectionError("injected: checkpoint push severed")
            await push_checkpoint_blocks(
                addr, present, [parents.get(h) for h in present], k, v,
                kv_format=self.dist.manager.kv_format,
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — replication is best-effort
            if isinstance(e, KvFormatError):
                # mixed-precision fleet: typed, counted (docs/kvbm.md
                # mixed-fleet rules)
                self.format_refusals += 1
            self.push_failures += 1
            self.blocks_dropped += len(present)
            self._poison(present)
            if isinstance(e, KvFormatError) or getattr(
                e, "ckpt_ineligible", False
            ):
                # structural refusal (wrong kv_format, no kvbm tier,
                # block-geometry mismatch): this never heals while the
                # instance lives, and a TTL quarantine would re-select
                # the same ring successor and shed a batch every
                # expiry — exclude it from checkpoint peering durably
                # (pull roles unaffected)
                self.dist.note_checkpoint_ineligible(inst)
            elif not getattr(e, "peer_blameless", False):
                # peer_blameless = our own oversized batch: the healthy
                # peer must not lose its pull/owner/hint roles for it
                self.dist.note_peer_failure(inst)
            logger.warning(
                "kv checkpoint push to %x (%s) failed: %s", inst, addr, e
            )
            return
        self.blocks_pushed += len(present)
        self.bytes_pushed += int(k.nbytes) + int(v.nbytes)

    def stats(self) -> dict:
        out = {
            "kvbm_ckpt_blocks_staged": self.blocks_staged,
            "kvbm_ckpt_blocks_pushed": self.blocks_pushed,
            "kvbm_ckpt_bytes_pushed": self.bytes_pushed,
            "kvbm_ckpt_blocks_dropped": self.blocks_dropped,
            "kvbm_ckpt_push_failures": self.push_failures,
            "kvbm_ckpt_format_refusals": self.format_refusals,
            "kvbm_ckpt_queue_depth": len(self._queue),
        }
        if self.last_peer is not None:
            out["kvbm_ckpt_last_peer"] = f"{self.last_peer:x}"
        return out

    def close(self):
        self._closed = True
        self._wake.set()
