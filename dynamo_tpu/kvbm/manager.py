"""KvBlockManager: tier policy + the engine connector.

Reference: lib/llm/src/block_manager.rs (KvBlockManager :99) and
block_manager/offload.rs (OffloadManager). The reference offloads a block
down the G1->G2->G3 chain when it is *registered* (hash bound); onboarding
walks the chain upward on a prefix-cache lookup miss. We do the same, but
the data path is a PIPELINE (docs/kvbm.md), not a sequence of inline
copies:

  * offload is WRITE-THROUGH at block-commit time, BATCHED per engine
    step: every `_commit_blocks` in a step stages its (hash, page) pairs;
    the engine's end-of-step `flush_step()` submits ONE `extract_pages`
    gather for all of them onto the serial device executor. Because every
    later write to those pages is itself a device op queued behind ours on
    the same executor, the gather always reads the pre-eviction contents —
    no device read-back is ever needed at eviction time (the reference
    needs its CUDA block_copy.cu + bounce buffers for this; XLA gather +
    serialized execution makes it free of synchronization hazards). The
    gather job only DISPATCHES (XLA execution is async); the device->host
    copy, the G2 store, and any G2->G3 cascade + file I/O run on a
    dedicated `kvbm-tier` thread, so the device executor loses only the
    dispatch microseconds per step.
  * the staged->stored path is a BOUNDED queue: when the tier thread falls
    behind, the OLDEST in-flight batch is dropped (blocks are unreferenced
    cache copies — dropping loses a future cache hit, never correctness)
    rather than stalling the step loop; drops are counted.
  * onboard happens at admission: after the device prefix cache
    (PageAllocator.acquire_cached) is consulted, the engine probes the
    tiers for the NEXT hashes in the chain; hits are scatter-injected
    (`inject_pages`) into freshly allocated device pages before prefill,
    extending the cached prefix and skipping that prefill compute. Under
    DYN_SCHED_POLICY=sla the engine first compares the tiers' observed
    per-block load latency against the slot's TTFT headroom and falls
    back to recompute when onboarding would blow the deadline.

DYN_KVBM_PIPELINE=0 restores the seed's inline per-commit offload (one
gather + store per `_commit_blocks` call, all on the device executor) —
kept as the bench_kv_cache.py before/after arm and as a safety valve.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, List, Optional, Sequence, Tuple

import numpy as np

from ..runtime import faults
from .storage import EVICTION_POLICIES, DiskTier, HostTier

logger = logging.getLogger(__name__)


def _parse_eviction(spec: Optional[str]) -> Tuple[str, str]:
    """DYN_KVBM_EVICTION: a single policy (`lru`) applies to both tiers;
    `host=lfu,disk=lru` sets them independently. Unknown spellings fall
    back to lru (an eviction-policy typo must not take the worker down)."""
    import os

    spec = spec if spec is not None else os.environ.get("DYN_KVBM_EVICTION")
    if not spec:
        return "lru", "lru"
    spec = spec.strip().lower()
    if "=" not in spec:
        if spec not in EVICTION_POLICIES:
            logger.warning("DYN_KVBM_EVICTION=%r unknown; using lru", spec)
            spec = "lru"
        return spec, spec
    out = {"host": "lru", "disk": "lru"}
    for part in spec.split(","):
        tier, _, pol = part.partition("=")
        tier, pol = tier.strip(), pol.strip()
        if tier not in out or pol not in EVICTION_POLICIES:
            logger.warning("DYN_KVBM_EVICTION part %r unknown; ignoring", part)
            continue
        out[tier] = pol
    return out["host"], out["disk"]


@dataclass
class KvbmConfig:
    host_blocks: int = 0  # G2 capacity (0 disables the tier)
    disk_blocks: int = 0  # G3 capacity (0 disables the tier)
    disk_path: Optional[str] = None
    eviction: Optional[str] = None  # None -> DYN_KVBM_EVICTION -> lru


class KvBlockManager:
    """Owns the G2/G3 tiers and the offload/onboard policy."""

    def __init__(self, cfg: KvbmConfig, block_shape: tuple, dtype,
                 kv_format: str = "none"):
        self.cfg = cfg
        self.block_shape = tuple(block_shape)
        self.dtype = dtype
        # quantized-KV page format this manager's tiers hold (docs/kvbm.md
        # "Quantized KV format"): under int8/int4 a block is ONE PACKED
        # uint8 row per layer (q bytes + per-page-per-head scales,
        # ops/kv_quant.py host layout) — tier capacity at fixed bytes
        # grows 2x/4x, and the format travels in the peer-pull handshake
        # so mixed-precision fleets fail typed (KvFormatError)
        self.kv_format = str(kv_format)
        # K+V bytes per block: the data plane sizes its inline-vs-executor
        # serve decision off this
        self.block_nbytes = 2 * int(np.prod(block_shape)) * np.dtype(dtype).itemsize
        if cfg.disk_blocks > 0 and not cfg.disk_path:
            raise ValueError("kvbm_disk_blocks > 0 requires kvbm_disk_path")
        host_policy, disk_policy = _parse_eviction(cfg.eviction)
        self.host: Optional[HostTier] = (
            HostTier(cfg.host_blocks, block_shape, dtype, policy=host_policy)
            if cfg.host_blocks > 0
            else None
        )
        self.disk: Optional[DiskTier] = (
            DiskTier(cfg.disk_blocks, block_shape, dtype, cfg.disk_path,
                     policy=disk_policy)
            if cfg.disk_blocks > 0
            else None
        )
        self._lock = threading.Lock()  # store runs on the kvbm-tier thread
        self.offloaded_blocks = 0
        self.onboarded_blocks = 0
        self.disk_evictions = 0
        self.dropped_blocks = 0
        # hashes that fell off the tier chain entirely since the last
        # drain: the announcement mesh must retract them, or peers keep
        # stale owner entries and probe onto dead blocks (the bounded-tier
        # + worker-churn resurrection bug)
        self._evicted_pending: List[int] = []
        # per-tier per-block load latency EWMA (ms): feeds the onboard
        # budget (estimate_load_ms). None until first observed — a cold
        # tier never defers an onboard (same rule as the scheduler's
        # CostModel: never-observed = no constraint).
        self._load_ms: dict = {"host": None, "disk": None}

    # -- store path (kvbm-tier thread; device-exec thread on the legacy
    # inline path) ------------------------------------------------------- #

    def store(self, seq_hash: int, k: np.ndarray, v: np.ndarray,
              parent: Optional[int] = None):
        """Insert one block at the top of the G2->G3 chain, cascading the
        host tier's eviction down to disk. `parent` = preceding chain hash
        when known (prefix-aware eviction protection)."""
        with self._lock:
            if self.host is not None:
                evicted = self.host.put(seq_hash, k, v, parent=parent)
                self.offloaded_blocks += 1
                if evicted is not None:
                    old_hash, old_k, old_v, old_parent = evicted
                    if self.disk is not None:
                        dropped = self.disk.put(
                            old_hash, old_k, old_v, parent=old_parent
                        )
                        if dropped is not None:
                            self.dropped_blocks += 1
                            self._evicted_pending.append(int(dropped))
                        self.disk_evictions += 1
                    else:
                        self.dropped_blocks += 1
                        self._evicted_pending.append(int(old_hash))
            elif self.disk is not None:
                dropped = self.disk.put(seq_hash, k, v, parent=parent)
                if dropped is not None:
                    self.dropped_blocks += 1
                    self._evicted_pending.append(int(dropped))
                self.offloaded_blocks += 1

    def drain_evicted(self) -> List[int]:
        """Hashes dropped from ALL tiers since the last drain (the
        announcement mesh retracts these as `evicted`).

        Re-checked against the CURRENT tier contents before handing out:
        a hash evicted and then RE-STORED between the drop and this drain
        (same-prefix traffic re-offloading, a peer promotion) is still
        held here — retracting it would tell peers to forget a live
        owner, and nothing re-announces until the block churns again."""
        with self._lock:
            pending, self._evicted_pending = self._evicted_pending, []
            out: List[int] = []
            seen = set()
            for h in pending:
                if h in seen:
                    continue
                seen.add(h)
                present = (
                    self.host is not None and self.host.has(h)
                ) or (self.disk is not None and self.disk.has(h))
                if not present:
                    out.append(h)
            return out

    def all_hashes(self) -> List[int]:
        """Every block hash held in any tier (the announcement-mesh
        sync-reply payload)."""
        with self._lock:
            out = set()
            if self.host is not None:
                out.update(self.host._by_hash)
            if self.disk is not None:
                out.update(self.disk._by_hash)
            return sorted(out)

    def has(self, seq_hash: int) -> bool:
        with self._lock:
            if self.host is not None and self.host.has(seq_hash):
                return True
            return self.disk is not None and self.disk.has(seq_hash)

    # -- lookup path (event loop thread) --------------------------------- #

    def match_prefix(self, hashes: Sequence[int]) -> List[int]:
        """Longest leading run of `hashes` present in any tier."""
        out: List[int] = []
        for h in hashes:
            if self.has(h):
                out.append(h)
            else:
                break
        return out

    def estimate_load_ms(self, hashes: Sequence[int]) -> Optional[float]:
        """Projected load_blocks latency for `hashes` from the per-tier
        EWMAs. None when any needed tier has never been observed (cold
        tiers never defer an onboard) or when a hash is not tiered here
        (remote pull cost is unknowable locally)."""
        with self._lock:
            total = 0.0
            for h in hashes:
                if self.host is not None and self.host.has(h):
                    ms = self._load_ms["host"]
                elif self.disk is not None and self.disk.has(h):
                    ms = self._load_ms["disk"]
                else:
                    return None
                if ms is None:
                    return None
                total += ms
            return total

    def load_blocks(
        self, hashes: Sequence[int]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Fetch blocks (host first, then disk, promoting disk hits to host)
        stacked on a leading axis: [n, *block_shape]."""
        ks, vs = [], []
        with self._lock:
            for h in hashes:
                t0 = time.perf_counter()
                got = self.host.get(h) if self.host is not None else None
                src = "host"
                if got is None and self.disk is not None:
                    got = self.disk.get(h)
                    src = "disk"
                    if got is not None and self.host is not None:
                        # promotion carries the chain link: without it a
                        # just-promoted chain loses its prefix-aware
                        # descendant protection in the host tier
                        evicted = self.host.put(
                            h, got[0], got[1],
                            parent=self.disk._parent.get(h),
                        )
                        if evicted is not None:
                            old_hash, old_k, old_v, old_parent = evicted
                            dropped = self.disk.put(
                                old_hash, old_k, old_v, parent=old_parent
                            )
                            if dropped is not None:
                                self.dropped_blocks += 1
                                self._evicted_pending.append(int(dropped))
                            self.disk_evictions += 1
                if got is None:
                    raise KeyError(f"KVBM block {h} vanished between probe and load")
                # copy: get() returns views into the tier pools, and a later
                # promotion in this same loop may evict+overwrite those slots
                ks.append(np.array(got[0]))
                vs.append(np.array(got[1]))
                # per-tier load-latency EWMA feeding estimate_load_ms
                ms = (time.perf_counter() - t0) * 1000.0
                prev = self._load_ms[src]
                self._load_ms[src] = (
                    ms if prev is None else 0.8 * prev + 0.2 * ms
                )
            self.onboarded_blocks += len(hashes)
        return np.stack(ks), np.stack(vs)

    def read_blocks(
        self, hashes: Sequence[int]
    ) -> Tuple[List[int], np.ndarray, np.ndarray]:
        """Read-only fetch for the session-checkpoint replicator: no
        promotion, no hit/miss/onboard accounting, no recency touch — a
        background copy must not distort the tier stats or eviction order
        the serving path depends on. Missing hashes are silently skipped
        (evicted between stage and push: the checkpoint just loses that
        block, same drop-not-stall discipline as the offload queue).
        Returns (present_hashes, k [n,...], v [n,...])."""
        present: List[int] = []
        ks, vs = [], []
        with self._lock:
            for h in hashes:
                for tier in (self.host, self.disk):
                    if tier is None:
                        continue
                    slot = tier._by_hash.get(h)
                    if slot is not None:
                        present.append(int(h))
                        # copy: the views die with the next eviction
                        ks.append(np.array(tier._k[slot]))
                        vs.append(np.array(tier._v[slot]))
                        break
        if not present:
            return [], np.empty((0,)), np.empty((0,))
        return present, np.stack(ks), np.stack(vs)

    def flush(self):
        """Persist the disk tier's index (engine close / checkpoint)."""
        with self._lock:
            if self.disk is not None:
                self.disk.flush()

    def clear(self) -> int:
        """Drop every tiered block (admin clear-kv-blocks route)."""
        with self._lock:
            n = 0
            if self.host is not None:
                n += self.host.clear()
            if self.disk is not None:
                n += self.disk.clear()
                self.disk.flush()  # persist the now-empty index
            return n

    def stats(self) -> dict:
        # the event loop reads while the tier thread stores: the lock buys
        # a consistent counter+tier snapshot (GUARDED_STATE)
        with self._lock:
            out = {
                "kvbm_offloaded_blocks": self.offloaded_blocks,
                "kvbm_onboarded_blocks": self.onboarded_blocks,
                "kvbm_disk_evictions": self.disk_evictions,
                "kvbm_dropped_blocks": self.dropped_blocks,
            }
            if self.host is not None:
                out.update({f"kvbm_{k}": v for k, v in self.host.stats().items()})
                out["kvbm_host_eviction_policy"] = self.host.policy
            if self.disk is not None:
                out.update({f"kvbm_{k}": v for k, v in self.disk.stats().items()})
                out["kvbm_disk_eviction_policy"] = self.disk.policy
            for tier, ms in self._load_ms.items():
                if ms is not None:
                    out[f"kvbm_{tier}_load_ms_per_block"] = round(ms, 3)
            return out


@dataclass
class _OffloadBatch:
    """One step's coalesced commits, gathered on-device, awaiting the tier
    thread. `k`/`v` are jax device arrays ([layers, n, page, heads, dim]);
    np.asarray on the tier thread performs the device->host copy."""

    hashes: List[int]
    parents: List[Optional[int]]
    k: object = None
    v: object = None
    ready: bool = False  # gather dispatched (k/v populated)
    dropped: bool = False  # backpressure victim: tier thread must skip it
    # "offload" = this worker's own session commits (checkpoint-staged);
    # "promotion" = peer-pulled blocks entering the host tier (already
    # durable on the peer — replicating them would waste the data plane
    # AND crowd this worker's own sessions out of the bounded stage)
    origin: str = "offload"


class KvbmConnector:
    """Engine-side glue (reference block_manager/connector/scheduler.rs:
    the piece that integrates the pool with the engine's forward pass).

    Holds a reference to the JaxEngine for its jitted extract/inject ops
    and its serial device executor; see module docstring for the pipeline
    stages and the ordering argument that makes write-through offload
    race-free.
    """

    def __init__(self, engine, manager: KvBlockManager):
        from ..runtime.config import env_bool

        self.engine = engine
        self.manager = manager
        self.pipelined = env_bool("DYN_KVBM_PIPELINE", True)
        # cluster KV fabric (docs/kvbm.md): admission may onboard blocks
        # from a PEER worker's tiers over the data plane. Off = local
        # tiers only (the pre-fabric behavior).
        self.peer_pull = env_bool("DYN_KVBM_PEER_PULL", True)
        import os

        try:
            self.queue_cap = max(
                int(os.environ.get("DYN_KVBM_OFFLOAD_QUEUE") or 8), 1
            )
        except ValueError:
            self.queue_cap = 8
        self._pending = 0
        self._pending_lock = threading.Lock()  # legacy inline path only
        # pipeline state — ALL of it guarded by _offload_cv's lock: the
        # event loop stages and flushes, the device-exec thread marks
        # batches ready, the kvbm-tier thread consumes (GUARDED_STATE)
        self._offload_cv = threading.Condition()
        self._staged: List[Tuple[int, int, Optional[int]]] = []  # (hash, phys_page, parent)
        self._queue: Deque[_OffloadBatch] = deque()
        self._inflight_hashes: set = set()  # staged or queued, pre-store
        self._processing = 0  # blocks of the batch the tier thread holds
        self._tier_thread: Optional[threading.Thread] = None
        self._stopped = False
        # counters (read via stats() under the cv lock)
        self.offload_commit_calls = 0
        self.offload_gathers = 0
        self.offload_batches_dropped = 0
        self.offload_blocks_dropped = 0
        self.offload_failures = 0
        self.onboard_recompute_fallbacks = 0
        # per-source onboard decision accounting (cluster KV fabric): how
        # many admission blocks came from the local tiers, from a peer
        # pull, and how many the budget handed back to recompute
        self.onboard_src_local_blocks = 0
        self.onboard_src_peer_blocks = 0
        self.onboard_src_recompute_blocks = 0
        # kvbm/distributed.py attaches itself here: cross-worker probe/pull
        # (the G4 role — peer memory as the tier below disk)
        self.distributed = None

    # -- offload (event loop: stage at commit, flush once per step) ------ #

    def offload_commit(self, seq_hashes: List[int], phys_pages: List[int],
                       parent: Optional[int] = None):
        """Write-through: snapshot the just-committed device pages into G2.
        Pipelined (default): stage the pairs; the engine's end-of-step
        `flush_step()` coalesces every stage from this step into one
        gather. Legacy (DYN_KVBM_PIPELINE=0): one gather + inline store per
        call on the device executor. `parent` = hash chained immediately
        before `seq_hashes[0]` (None at a chain head)."""
        if not self.pipelined:
            self._offload_commit_inline(seq_hashes, phys_pages, parent)
            return
        # probe the tiers BEFORE taking the cv: manager._lock nests under
        # _offload_cv nowhere (one global lock order, race-lock-order)
        missing = {h for h in seq_hashes if not self.manager.has(h)}
        with self._offload_cv:
            self.offload_commit_calls += 1
            prev = parent
            for h, p in zip(seq_hashes, phys_pages):
                if h in missing and h not in self._inflight_hashes:
                    self._staged.append((h, p, prev))
                    self._inflight_hashes.add(h)
                prev = h

    def flush_step(self):
        """Submit ONE gather for everything staged this step (engine step
        loop, once per `_step_once`). The gather job runs on the device
        executor but only dispatches; the device->host copy and tier
        stores happen on the kvbm-tier thread."""
        with self._offload_cv:
            if self._stopped or not self._staged:
                return
            staged, self._staged = self._staged, []
            batch = _OffloadBatch(
                hashes=[h for h, _, _ in staged],
                parents=[par for _, _, par in staged],
            )
            # backpressure: bound the not-yet-stored batches; the OLDEST
            # uncommitted batch is the least valuable (most likely already
            # superseded or about to be re-requested) — drop it, count it
            while len(self._queue) >= self.queue_cap:
                victim = self._queue.popleft()
                victim.dropped = True
                self.offload_batches_dropped += 1
                self.offload_blocks_dropped += len(victim.hashes)
                self._inflight_hashes.difference_update(victim.hashes)
            self._queue.append(batch)
            self.offload_gathers += 1
            self._ensure_tier_thread()
        # pad the gather to a pow2 page-count bucket (pad rows read the
        # scratch page and are never stored): a varying batch size would
        # compile a fresh extract_pages variant per distinct size —
        # unbounded compile space; buckets bound it at log2(max_batch)
        n = len(staged)
        bucket = 1 << (n - 1).bit_length()
        pages = np.zeros((bucket,), np.int32)
        pages[:n] = [p for _, p, _ in staged]
        eng = self.engine

        def run_gather():
            import jax.numpy as jnp

            try:
                k, v = eng._extract_pages(eng.kv_k, eng.kv_v, jnp.asarray(pages))
            except Exception as e:  # noqa: BLE001 — a failed gather loses
                # cache copies, never correctness; drop the batch
                logger.warning("KVBM offload gather failed: %s", e)
                with self._offload_cv:
                    if not batch.dropped:
                        # lost cache copies are DROPPED blocks wherever
                        # they die — dashboards alarm on one counter. A
                        # backpressure victim was already counted when it
                        # left the queue; its failing gather adds nothing.
                        self.offload_failures += 1
                        self.offload_blocks_dropped += len(batch.hashes)
                        self._inflight_hashes.difference_update(batch.hashes)
                    batch.dropped = True
                    batch.ready = True
                    self._offload_cv.notify_all()
                return
            with self._offload_cv:
                batch.k, batch.v = k, v
                batch.ready = True
                self._offload_cv.notify_all()

        # the device executor orders this gather before any later rewrite
        # of the same pages; _timed accrues its (dispatch-only) cost to
        # dispatch_kvbm_offload_* so the bench can see the µs stolen
        eng._device_exec.submit(eng._timed(run_gather, "kvbm_offload"))

    def stage_promotion(self, hashes: Sequence[int],
                        parents: Sequence[Optional[int]], k, v):
        """Promote peer-pulled blocks into the host tier OFF the onboard
        critical path: enqueue a READY batch for the kvbm-tier thread
        (same bounded queue + drop-oldest backpressure as offload
        write-through). Losing a promotion under pressure loses a future
        local hit, never correctness — the peer still owns the block."""
        # _store_batch expects [layers, n, ...] like a device gather
        # (peer pulls arrive per-block [n, layers, ...] — fp typed rows or
        # quantized packed uint8 rows, either way a plain swapaxes)
        batch = _OffloadBatch(
            hashes=[int(h) for h in hashes],
            parents=list(parents),
            k=np.asarray(k).swapaxes(0, 1),
            v=np.asarray(v).swapaxes(0, 1),
            ready=True,
            origin="promotion",
        )
        with self._offload_cv:
            if self._stopped:
                return
            while len(self._queue) >= self.queue_cap:
                victim = self._queue.popleft()
                victim.dropped = True
                self.offload_batches_dropped += 1
                self.offload_blocks_dropped += len(victim.hashes)
                self._inflight_hashes.difference_update(victim.hashes)
            self._queue.append(batch)
            self._inflight_hashes.update(batch.hashes)
            self._ensure_tier_thread()
            self._offload_cv.notify_all()

    def _ensure_tier_thread(self):
        """Caller holds _offload_cv."""
        if self._tier_thread is None or not self._tier_thread.is_alive():
            self._tier_thread = threading.Thread(
                target=self._tier_loop, name="kvbm-tier", daemon=True
            )
            self._tier_thread.start()

    def _tier_loop(self):
        """Dedicated tier thread: device->host copy, G2 store, G2->G3
        cascade and G3 file I/O — everything the seed ran on the device
        executor past the gather. One batch at a time, FIFO."""
        while True:
            with self._offload_cv:
                while not self._stopped and not (
                    self._queue and self._queue[0].ready
                ):
                    self._offload_cv.wait()
                if self._stopped and not self._queue:
                    return
                batch = self._queue[0]
                if not batch.ready:
                    # stopped with an un-gathered batch queued: nothing to
                    # store — the device job will never mark it ready.
                    # These are lost cache copies like any other drop.
                    self._queue.popleft()
                    self.offload_batches_dropped += 1
                    self.offload_blocks_dropped += len(batch.hashes)
                    self._inflight_hashes.difference_update(batch.hashes)
                    continue
                self._queue.popleft()
                self._processing = len(batch.hashes)
            try:
                if batch.dropped:
                    continue
                try:
                    self._store_batch(batch)
                except faults.FaultError as e:
                    # dynochaos kvbm.offload `error`: the batch is dropped,
                    # counted, and the stream never notices — offload is a
                    # cache write, not part of any request's critical path
                    logger.warning("KVBM offload batch dropped (%s)", e)
                    with self._offload_cv:
                        self.offload_failures += 1
                        self.offload_blocks_dropped += len(batch.hashes)
                        self._inflight_hashes.difference_update(batch.hashes)
                except Exception:  # noqa: BLE001 — the tier thread must not die
                    logger.exception("KVBM offload store failed; batch dropped")
                    with self._offload_cv:
                        self.offload_failures += 1
                        self.offload_blocks_dropped += len(batch.hashes)
                        self._inflight_hashes.difference_update(batch.hashes)
            finally:
                with self._offload_cv:
                    self._processing = 0

    def _store_batch(self, batch: _OffloadBatch):
        f = faults.FAULTS
        if f.enabled:
            act = f.check("kvbm.offload")
            if act == "error":
                raise faults.FaultError("injected fault at kvbm.offload")
            if act == "delay":
                time.sleep(0.05)
        # host_pack_pages blocks until the async gather lands — on THIS
        # thread, not the device executor. fp: the seed's np.asarray;
        # quantized: packed uint8 [L, n, PB] rows (q bytes + scales).
        # [layers, n, ...] -> per-block [n, ...]
        from ..ops.kv_quant import host_pack_pages

        k_np = host_pack_pages(batch.k).swapaxes(0, 1)
        v_np = host_pack_pages(batch.v).swapaxes(0, 1)
        for i, h in enumerate(batch.hashes):
            self.manager.store(h, k_np[i], v_np[i], parent=batch.parents[i])
        with self._offload_cv:
            self._inflight_hashes.difference_update(batch.hashes)
        if self.distributed is not None:
            self.distributed.announce_threadsafe("stored", batch.hashes)
            self._announce_evictions()
            # session checkpointing (docs/fault_tolerance.md): every block
            # this worker COMMITS is also staged for replication to a
            # peer's G2 — bounded (newest refused), never blocks this
            # thread. Promotion batches (peer-pulled blocks) are not
            # staged: they are already durable on the peer that served
            # them, and re-pushing them would crowd this worker's own
            # live sessions out of the bounded stage
            ck = self.distributed.checkpointer
            if ck is not None and batch.origin == "offload":
                ck.stage_threadsafe(batch.hashes, batch.parents)

    def _announce_evictions(self):
        """Retract fully-dropped hashes from the mesh (any thread)."""
        if self.distributed is None:
            return
        evicted = self.manager.drain_evicted()
        if evicted:
            self.distributed.announce_threadsafe("evicted", evicted)

    def _offload_commit_inline(self, seq_hashes: List[int], phys_pages: List[int],
                               parent: Optional[int] = None):
        """Seed-shaped inline path (DYN_KVBM_PIPELINE=0): one gather +
        synchronous store per commit call, all on the device executor.
        Parents chain through exactly like the pipeline, so prefix-aware
        eviction behaves identically on both arms."""
        todo = []
        prev = parent
        for h, p in zip(seq_hashes, phys_pages):
            if not self.manager.has(h):
                todo.append((h, p, prev))
            prev = h
        if not todo:
            return
        with self._offload_cv:
            self.offload_commit_calls += 1
            self.offload_gathers += 1
        eng = self.engine
        hashes = [h for h, _, _ in todo]
        parents = [par for _, _, par in todo]
        pages = np.array([p for _, p, _ in todo], np.int32)

        def run_extract():
            import jax.numpy as jnp

            from ..ops.kv_quant import host_pack_pages

            k, v = eng._extract_pages(eng.kv_k, eng.kv_v, jnp.asarray(pages))
            # [layers, n, ...] -> per-block [layers, ...] (fp typed rows
            # or quantized packed uint8 rows, same as the pipelined path)
            k_np = host_pack_pages(k).swapaxes(0, 1)
            v_np = host_pack_pages(v).swapaxes(0, 1)
            for i, h in enumerate(hashes):
                self.manager.store(h, k_np[i], v_np[i], parent=parents[i])
            if self.distributed is not None:
                self.distributed.announce_threadsafe("stored", hashes)
                self._announce_evictions()
                ck = self.distributed.checkpointer
                if ck is not None:
                    ck.stage_threadsafe(hashes, parents)

        with self._pending_lock:
            self._pending += 1

        def done(fut):
            with self._pending_lock:
                self._pending -= 1
            exc = fut.exception()
            if exc is not None:
                logger.warning("KVBM offload failed: %s", exc)

        eng._device_exec.submit(
            eng._timed(run_extract, "kvbm_offload")
        ).add_done_callback(done)

    # -- onboard (called at admission) ----------------------------------- #

    def probe(self, hashes: Sequence[int], hint_instance: Optional[int] = None,
              hint_blocks: int = 0) -> List[int]:
        """Longest onboardable prefix: local tiers, extended by remote
        owners when the distributed mesh is attached (G4 role). The
        router-supplied holder hint (`hint_instance` holds the first
        `hint_blocks` entries of THIS slice per the router's radix index)
        extends coverage past what the announcement mesh has mirrored."""
        local = self.manager.match_prefix(hashes)
        if (
            self.peer_pull and self.distributed is not None
            and len(local) < len(hashes)
        ):
            return list(local) + self.distributed.extend_prefix(
                list(hashes)[len(local):],
                hint_instance=hint_instance,
                hint_blocks=max(hint_blocks - len(local), 0),
            )
        return local

    def estimate_onboard_ms(self, hashes: Sequence[int]) -> Optional[float]:
        """Projected tier-load latency for an onboard of `hashes` (None =
        unknown; the engine only defers to recompute on a KNOWN blowout)."""
        return self.manager.estimate_load_ms(hashes)

    def budget_onboard(
        self,
        hashes: List[int],
        headroom_ms: Optional[float],
        recompute_ms_per_block: Optional[float],
        hint_instance: Optional[int] = None,
    ) -> Tuple[List[int], str]:
        """Three-arm onboard budget (docs/kvbm.md cluster KV fabric): the
        cheapest source wins per span — local-tier load vs per-peer
        transfer rate vs recompute — and a cold/slow peer never blocks
        TTFT past the slot's headroom.

        Returns (hashes_to_onboard, decision) with decision one of
        `full` (onboard everything probed), `trim-local` (keep the
        locally-tiered prefix, recompute the peer tail), `recompute`
        (skip the onboard entirely). Unknown costs never constrain: a
        cold tier/peer/cost-model keeps the full onboard, the same rule
        as the scheduler's CostModel."""
        if not hashes:
            return hashes, "full"
        local_mask = [self.manager.has(h) for h in hashes]
        n_total = len(hashes)
        # cost of the full onboard: local part at tier EWMA + peer part at
        # per-peer transfer EWMA; any unknown component -> unconstrained
        local_part = [h for h, m in zip(hashes, local_mask) if m]
        peer_part = [h for h, m in zip(hashes, local_mask) if not m]
        est_local = (
            self.manager.estimate_load_ms(local_part) if local_part else 0.0
        )
        if peer_part and (self.distributed is None or not self.peer_pull):
            # probe() can't have included peer blocks in that case, but a
            # racing eviction may have demoted a local hash: recompute it
            est_peer = None
        elif peer_part:
            est_peer = self.distributed.estimate_pull_ms(
                peer_part, hint_instance=hint_instance
            )
        else:
            est_peer = 0.0
        est_full = (
            est_local + est_peer
            if est_local is not None and est_peer is not None else None
        )
        if headroom_ms is None or est_full is None or est_full <= headroom_ms:
            self._count_onboard(len(local_part), len(peer_part), 0)
            return hashes, "full"
        if recompute_ms_per_block is None:
            # blown headroom but no recompute observation yet: we cannot
            # prove any alternative cheaper — keep the onboard
            self._count_onboard(len(local_part), len(peer_part), 0)
            return hashes, "full"
        # arm B: keep the locally-tiered PREFIX, recompute the rest (the
        # slow peer tail is the usual blowout); arm C: full recompute
        n_local_prefix = 0
        for m in local_mask:
            if not m:
                break
            n_local_prefix += 1
        est_prefix = (
            self.manager.estimate_load_ms(hashes[:n_local_prefix])
            if n_local_prefix else 0.0
        )
        cost_b = (
            est_prefix + recompute_ms_per_block * (n_total - n_local_prefix)
            if est_prefix is not None else None
        )
        cost_c = recompute_ms_per_block * n_total
        best, decision = est_full, "full"
        if cost_c < best:
            best, decision = cost_c, "recompute"
        if cost_b is not None and n_local_prefix and cost_b < best:
            best, decision = cost_b, "trim-local"
        if decision == "full":
            self._count_onboard(len(local_part), len(peer_part), 0)
            return hashes, "full"
        if decision == "trim-local":
            kept = hashes[:n_local_prefix]
            self._count_onboard(len(kept), 0, n_total - len(kept))
            self.note_onboard_recompute()
            return kept, "trim-local"
        self._count_onboard(0, 0, n_total)
        self.note_onboard_recompute()
        return [], "recompute"

    def _count_onboard(self, n_local: int, n_peer: int, n_recompute: int):
        with self._offload_cv:
            self.onboard_src_local_blocks += n_local
            self.onboard_src_peer_blocks += n_peer
            self.onboard_src_recompute_blocks += n_recompute

    def note_onboard_recompute(self):
        """The engine skipped (part of) an onboard whose projected load
        latency exceeded the slot's TTFT headroom and lost to recompute
        (docs/kvbm.md onboard budget)."""
        with self._offload_cv:
            self.onboard_recompute_fallbacks += 1

    def any_checkpoint(self, hashes: Sequence[int]) -> bool:
        """True when any of `hashes` is a session-checkpoint replica —
        pushed INTO this worker's tiers by a peer's checkpointer, or
        mesh-announced as checkpointed elsewhere. Drives the engine's
        resume-source classification for migrated requests."""
        return (
            self.distributed is not None
            and self.distributed.any_checkpoint(hashes)
        )

    def load(self, hashes: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        return self.manager.load_blocks(hashes)

    async def load_async(self, hashes: Sequence[int], run,
                         hint_instance: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """Onboard path: local tier reads ride the engine's device/IO
        executor (`run`), remote blocks pull point-to-point from their
        owner's data plane (announced owner, falling back to the router's
        holder hint) and are PROMOTED into the local host tier so repeat
        hits stay local. Raises KeyError on any miss (the engine falls
        back to prefilling that span); a dynochaos `kvbm.onboard` error or
        a typed KvTransferError (severed/unreachable peer) rides the same
        fallback."""
        f = faults.FAULTS
        if f.enabled:
            # FaultError propagates to _inject_onboard, which treats it
            # exactly like an evicted block: recompute that span
            await f.on("kvbm.onboard")
        local = [h for h in hashes if self.manager.has(h)]
        remote = [h for h in hashes if not self.manager.has(h)]
        # `hashes` is a contiguous onboard span: each hash's predecessor
        # is its chain parent (first unknown) — promotion keeps the links
        parent_of: dict = {}
        prev = None
        for h in hashes:
            parent_of[h] = prev
            prev = h
        parts: dict = {}
        if remote:
            if self.distributed is None or not self.peer_pull:
                raise KeyError(f"kvbm blocks {remote[:3]}... not tiered here")
            try:
                rk, rv = await self.distributed.pull_blocks(
                    remote, hint_instance=hint_instance
                )
            except KeyError:
                raise
            except Exception as e:  # noqa: BLE001 — dead peer / severed
                from ..llm.kv_transfer import KvFormatError

                if isinstance(e, KvFormatError):
                    # mixed-precision fleet: stays TYPED all the way up —
                    # the engine counts it (kv_format_mismatches) before
                    # falling back to recompute
                    raise
                # stream / unresolvable addr (KvTransferError) or any other
                # transport failure: the engine treats a KeyError as
                # "prefill that span instead"
                raise KeyError(f"kvbm remote pull failed: {e}") from e

            if self.pipelined:
                # promotion rides the tier thread, not the onboard
                # critical path (stage_promotion) — the slot's inject
                # proceeds immediately
                self.stage_promotion(
                    remote, [parent_of[h] for h in remote], rk, rv
                )
            else:
                def promote():
                    for i, h in enumerate(remote):
                        self.manager.store(h, rk[i], rv[i], parent=parent_of[h])

                await run(promote)
            if not local:
                # pull_blocks stacked in `hashes` order already — skip
                # the per-block restack copy (admission latency path)
                return rk, rv
            for i, h in enumerate(remote):
                parts[h] = (rk[i], rv[i])
        if local:
            if not remote:
                out = await run(self.manager.load_blocks, local)
                # disk→host promotion inside load_blocks can cascade
                # drops: retract them even on a read-only path (a worker
                # that mostly SERVES pulls would otherwise never drain)
                self._announce_evictions()
                return out
            lk, lv = await run(self.manager.load_blocks, local)
            self._announce_evictions()
            for i, h in enumerate(local):
                parts[h] = (lk[i], lv[i])
        ks = np.stack([parts[h][0] for h in hashes])
        vs = np.stack([parts[h][1] for h in hashes])
        return ks, vs

    def clear(self) -> int:
        n = self.manager.clear()
        if self.distributed is not None:
            self.distributed.announce("cleared", [])
        return n

    def pending_offloads(self) -> int:
        """In-flight write-through count: staged pairs + queued batches'
        blocks + the batch mid-store on the tier thread (pipeline) +
        legacy inline jobs (engine close() drains on this)."""
        with self._offload_cv:
            n = (
                len(self._staged)
                + sum(len(b.hashes) for b in self._queue)
                + self._processing
            )
        with self._pending_lock:
            n += self._pending
        return n

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Block (event-loop-free callers only) until every staged/queued
        offload is stored or dropped. Returns False on timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.pending_offloads() == 0:
                return True
            time.sleep(0.005)
        return self.pending_offloads() == 0

    def shutdown(self):
        """Stop the tier thread after the queue empties (engine close();
        call after an async drain)."""
        with self._offload_cv:
            self._stopped = True
            self._offload_cv.notify_all()
        t = self._tier_thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    def stats(self) -> dict:
        with self._offload_cv:
            queue_depth = len(self._queue)
            staged = len(self._staged)
            out = {
                "kvbm_offload_commit_calls": self.offload_commit_calls,
                "kvbm_offload_gathers": self.offload_gathers,
                "kvbm_offload_queue_depth": queue_depth,
                "kvbm_offload_staged_blocks": staged,
                "kvbm_offload_batches_dropped": self.offload_batches_dropped,
                "kvbm_offload_blocks_dropped": self.offload_blocks_dropped,
                "kvbm_offload_failures": self.offload_failures,
                "kvbm_onboard_recompute_fallbacks": self.onboard_recompute_fallbacks,
                "kvbm_onboard_src_local_blocks": self.onboard_src_local_blocks,
                "kvbm_onboard_src_peer_blocks": self.onboard_src_peer_blocks,
                "kvbm_onboard_src_recompute_blocks": self.onboard_src_recompute_blocks,
            }
        out.update(self.manager.stats())
        out["kvbm_pending_offloads"] = self.pending_offloads()
        if self.distributed is not None:
            out.update(self.distributed.stats())
        return out
