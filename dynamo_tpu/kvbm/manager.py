"""KvBlockManager: tier policy + the engine connector.

Reference: lib/llm/src/block_manager.rs (KvBlockManager :99) and
block_manager/offload.rs (OffloadManager). The reference offloads a block
down the G1->G2->G3 chain when it is *registered* (hash bound); onboarding
walks the chain upward on a prefix-cache lookup miss. We do the same:

  * offload is WRITE-THROUGH at block-commit time: the engine's
    `_commit_blocks` hands us (hashes, physical pages); we enqueue one XLA
    gather (`extract_pages`) on the engine's serial device executor and copy
    the result into the host pool. Because every later write to those pages
    is itself a device op queued behind ours on the same executor, the
    extract always reads the pre-eviction contents — no device read-back is
    ever needed at eviction time (the reference needs its CUDA
    block_copy.cu + bounce buffers for this; XLA gather + serialized
    execution makes it free of synchronization hazards).
  * onboard happens at admission: after the device prefix cache
    (PageAllocator.acquire_cached) is consulted, the engine probes the
    tiers for the NEXT hashes in the chain; hits are scatter-injected
    (`inject_pages`) into freshly allocated device pages before prefill,
    extending the cached prefix and skipping that prefill compute.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .storage import DiskTier, HostTier

logger = logging.getLogger(__name__)


@dataclass
class KvbmConfig:
    host_blocks: int = 0  # G2 capacity (0 disables the tier)
    disk_blocks: int = 0  # G3 capacity (0 disables the tier)
    disk_path: Optional[str] = None


class KvBlockManager:
    """Owns the G2/G3 tiers and the offload/onboard policy."""

    def __init__(self, cfg: KvbmConfig, block_shape: tuple, dtype):
        self.cfg = cfg
        self.block_shape = tuple(block_shape)
        self.dtype = dtype
        if cfg.disk_blocks > 0 and not cfg.disk_path:
            raise ValueError("kvbm_disk_blocks > 0 requires kvbm_disk_path")
        self.host: Optional[HostTier] = (
            HostTier(cfg.host_blocks, block_shape, dtype)
            if cfg.host_blocks > 0
            else None
        )
        self.disk: Optional[DiskTier] = (
            DiskTier(cfg.disk_blocks, block_shape, dtype, cfg.disk_path)
            if cfg.disk_blocks > 0
            else None
        )
        self._lock = threading.Lock()  # store runs on the device-exec thread
        self.offloaded_blocks = 0
        self.onboarded_blocks = 0
        self.disk_evictions = 0
        self.dropped_blocks = 0

    # -- store path (device executor thread) ----------------------------- #

    def store(self, seq_hash: int, k: np.ndarray, v: np.ndarray):
        """Insert one block at the top of the G2->G3 chain, cascading the
        host tier's LRU eviction down to disk."""
        with self._lock:
            if self.host is not None:
                evicted = self.host.put(seq_hash, k, v)
                self.offloaded_blocks += 1
                if evicted is not None:
                    old_hash, old_k, old_v = evicted
                    if self.disk is not None:
                        if self.disk.put(old_hash, old_k, old_v) is not None:
                            self.dropped_blocks += 1
                        self.disk_evictions += 1
                    else:
                        self.dropped_blocks += 1
            elif self.disk is not None:
                if self.disk.put(seq_hash, k, v) is not None:
                    self.dropped_blocks += 1
                self.offloaded_blocks += 1

    def all_hashes(self) -> List[int]:
        """Every block hash held in any tier (the announcement-mesh
        sync-reply payload)."""
        with self._lock:
            out = set()
            if self.host is not None:
                out.update(self.host._by_hash)
            if self.disk is not None:
                out.update(self.disk._by_hash)
            return sorted(out)

    def has(self, seq_hash: int) -> bool:
        with self._lock:
            if self.host is not None and self.host.has(seq_hash):
                return True
            return self.disk is not None and self.disk.has(seq_hash)

    # -- lookup path (event loop thread) --------------------------------- #

    def match_prefix(self, hashes: Sequence[int]) -> List[int]:
        """Longest leading run of `hashes` present in any tier."""
        out: List[int] = []
        for h in hashes:
            if self.has(h):
                out.append(h)
            else:
                break
        return out

    def load_blocks(
        self, hashes: Sequence[int]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Fetch blocks (host first, then disk, promoting disk hits to host)
        stacked on a leading axis: [n, *block_shape]."""
        ks, vs = [], []
        with self._lock:
            for h in hashes:
                got = self.host.get(h) if self.host is not None else None
                if got is None and self.disk is not None:
                    got = self.disk.get(h)
                    if got is not None and self.host is not None:
                        evicted = self.host.put(h, got[0], got[1])
                        if evicted is not None:
                            old_hash, old_k, old_v = evicted
                            if self.disk.put(old_hash, old_k, old_v) is not None:
                                self.dropped_blocks += 1
                            self.disk_evictions += 1
                if got is None:
                    raise KeyError(f"KVBM block {h} vanished between probe and load")
                # copy: get() returns views into the tier pools, and a later
                # promotion in this same loop may evict+overwrite those slots
                ks.append(np.array(got[0]))
                vs.append(np.array(got[1]))
            self.onboarded_blocks += len(hashes)
        return np.stack(ks), np.stack(vs)

    def flush(self):
        """Persist the disk tier's index (engine close / checkpoint)."""
        with self._lock:
            if self.disk is not None:
                self.disk.flush()

    def clear(self) -> int:
        """Drop every tiered block (admin clear-kv-blocks route)."""
        with self._lock:
            n = 0
            if self.host is not None:
                n += self.host.clear()
            if self.disk is not None:
                n += self.disk.clear()
                self.disk.flush()  # persist the now-empty index
            return n

    def stats(self) -> dict:
        # the event loop reads while the device-exec thread stores: the
        # lock buys a consistent counter+tier snapshot (GUARDED_STATE)
        with self._lock:
            out = {
                "kvbm_offloaded_blocks": self.offloaded_blocks,
                "kvbm_onboarded_blocks": self.onboarded_blocks,
                "kvbm_disk_evictions": self.disk_evictions,
                "kvbm_dropped_blocks": self.dropped_blocks,
            }
            if self.host is not None:
                out.update({f"kvbm_{k}": v for k, v in self.host.stats().items()})
            if self.disk is not None:
                out.update({f"kvbm_{k}": v for k, v in self.disk.stats().items()})
            return out


class KvbmConnector:
    """Engine-side glue (reference block_manager/connector/scheduler.rs:
    the piece that integrates the pool with the engine's forward pass).

    Holds a reference to the JaxEngine for its jitted extract/inject ops and
    its serial device executor; see module docstring for the ordering
    argument that makes write-through offload race-free.
    """

    def __init__(self, engine, manager: KvBlockManager):
        self.engine = engine
        self.manager = manager
        self._pending = 0
        self._pending_lock = threading.Lock()  # bumped on loop, dropped on exec thread
        # kvbm/distributed.py attaches itself here: cross-worker probe/pull
        # (the G4 role — peer memory as the tier below disk)
        self.distributed = None

    # -- offload (called on the event loop right after block commit) ----- #

    def offload_commit(self, seq_hashes: List[int], phys_pages: List[int]):
        """Write-through: snapshot the just-committed device pages into G2.
        Submitted to the engine's device executor so the gather is ordered
        before any later page rewrite."""
        todo = [
            (h, p)
            for h, p in zip(seq_hashes, phys_pages)
            if not self.manager.has(h)
        ]
        if not todo:
            return
        eng = self.engine
        hashes = [h for h, _ in todo]
        pages = np.array([p for _, p in todo], np.int32)

        def run_extract():
            import jax.numpy as jnp

            k, v = eng._extract_pages(eng.kv_k, eng.kv_v, jnp.asarray(pages))
            # [layers, n, page, heads, dim] -> per-block [layers, page, heads, dim]
            k_np = np.asarray(k).swapaxes(0, 1)
            v_np = np.asarray(v).swapaxes(0, 1)
            for i, h in enumerate(hashes):
                self.manager.store(h, k_np[i], v_np[i])
            if self.distributed is not None:
                self.distributed.announce_threadsafe("stored", hashes)

        with self._pending_lock:
            self._pending += 1

        def done(fut):
            with self._pending_lock:
                self._pending -= 1
            exc = fut.exception()
            if exc is not None:
                logger.warning("KVBM offload failed: %s", exc)

        eng._device_exec.submit(run_extract).add_done_callback(done)

    # -- onboard (called at admission) ----------------------------------- #

    def probe(self, hashes: Sequence[int]) -> List[int]:
        """Longest onboardable prefix: local tiers, extended by remote
        owners when the distributed mesh is attached (G4 role)."""
        local = self.manager.match_prefix(hashes)
        if self.distributed is not None and len(local) < len(hashes):
            return list(local) + self.distributed.extend_prefix(
                list(hashes)[len(local):]
            )
        return local

    def load(self, hashes: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        return self.manager.load_blocks(hashes)

    async def load_async(self, hashes: Sequence[int], run) -> Tuple[np.ndarray, np.ndarray]:
        """Onboard path: local tier reads ride the engine's device/IO
        executor (`run`), remote blocks pull point-to-point from their
        owner's data plane and are PROMOTED into the local host tier so
        repeat hits stay local. Raises KeyError on any miss (the engine
        falls back to prefilling that span)."""
        local = [h for h in hashes if self.manager.has(h)]
        remote = [h for h in hashes if not self.manager.has(h)]
        parts: dict = {}
        if remote:
            if self.distributed is None:
                raise KeyError(f"kvbm blocks {remote[:3]}... not tiered here")
            try:
                rk, rv = await self.distributed.pull_blocks(remote)
            except KeyError:
                raise
            except Exception as e:  # noqa: BLE001 — dead peer/network: the
                # engine treats a KeyError as "prefill that span instead"
                raise KeyError(f"kvbm remote pull failed: {e}") from e

            def promote():
                for i, h in enumerate(remote):
                    self.manager.store(h, rk[i], rv[i])

            await run(promote)
            for i, h in enumerate(remote):
                parts[h] = (rk[i], rv[i])
        if local:
            lk, lv = await run(self.manager.load_blocks, local)
            for i, h in enumerate(local):
                parts[h] = (lk[i], lv[i])
        ks = np.stack([parts[h][0] for h in hashes])
        vs = np.stack([parts[h][1] for h in hashes])
        return ks, vs

    def clear(self) -> int:
        n = self.manager.clear()
        if self.distributed is not None:
            self.distributed.announce("cleared", [])
        return n

    def pending_offloads(self) -> int:
        """In-flight write-through count (engine close() drains on this)."""
        with self._pending_lock:
            return self._pending

    def stats(self) -> dict:
        with self._pending_lock:
            pending = self._pending
        out = {**self.manager.stats(), "kvbm_pending_offloads": pending}
        if self.distributed is not None:
            out.update(self.distributed.stats())
        return out
