"""Distributed KVBM: cross-worker block sharing over the KV data plane.

The reference runs a KVBM leader/worker pair over ZMQ so one worker can
onboard blocks another worker offloaded (block_manager/distributed/
leader.rs:126, worker.rs:137) — the disagg-adjacent reuse that makes a
decode worker's admission hit on a prefill worker's offloaded prefix.

TPU-native redesign (no leader): a symmetric announcement mesh.
  * every KVBM-enabled worker announces stored/cleared block hashes on a
    discovery topic (kvbm_blocks/{ns}/{comp}) and serves block reads on
    its existing KV data plane (llm/kv_transfer.py; the server resolves
    `{"blocks": [...]}` handshakes straight from the tier manager).
  * every worker mirrors the announcements into hash -> {instance} plus
    the peers' data-plane addresses (DATA_PLANE_ROOT entries), so an
    admission probe extends the local tier prefix with remote hits at
    in-memory cost.
  * onboarding pulls the missing blocks point-to-point from ONE owner and
    write-throughs them into the local host tier (promotion), so repeat
    hits are local.

The remote-peer pool IS this build's G4 tier (reference CacheLevel G4,
block_manager.rs:63): same probe/onboard interface as G2/G3, backed by
another worker's memory instead of object storage.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

logger = logging.getLogger(__name__)

KVBM_TOPIC_FMT = "kvbm_blocks/{namespace}/{component}"


class KvbmDistributed:
    """The announcement mesh + remote pull for one worker's KVBM."""

    def __init__(
        self,
        drt,
        connector,  # kvbm.manager.KvbmConnector
        data_plane,  # llm.kv_transfer.KvDataPlaneServer (serves our blocks)
        namespace: str,
        component: str,
        instance_id: int,
    ):
        self.drt = drt
        self.connector = connector
        self.manager = connector.manager
        self.data_plane = data_plane
        self.topic = KVBM_TOPIC_FMT.format(namespace=namespace, component=component)
        self.instance_id = instance_id
        # hash -> instances that announced it; instance -> data plane addr
        self._owners: Dict[int, Set[int]] = {}
        self._addrs: Dict[int, str] = {}
        self._sub = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._task: Optional[asyncio.Task] = None
        self._addr_task: Optional[asyncio.Task] = None
        self._bg: set = set()
        self.remote_onboards = 0
        self.remote_blocks_pulled = 0
        # serve our tier blocks on the data plane
        if data_plane is not None:
            data_plane.kvbm_source = self.manager
        connector.distributed = self

    async def start(self):
        from ..llm.kv_transfer import DATA_PLANE_ROOT

        self._loop = asyncio.get_running_loop()
        if self.drt.discovery is None:
            return
        self._sub = await self.drt.discovery.subscribe(self.topic)
        self._task = asyncio.create_task(self._mirror_loop())
        watch = await self.drt.discovery.watch_prefix(DATA_PLANE_ROOT)
        for item in watch.snapshot:
            self._on_addr(item["key"], item["value"])
        self._addr_task = asyncio.create_task(self._addr_loop(watch))
        # announcements are fire-and-forget pub/sub: a worker that joins
        # AFTER peers offloaded (fresh decode replica, post-crash restart)
        # would never learn their tier contents — ask everyone to
        # re-announce (peers reply with their full hash sets)
        self.announce("sync_request", [])

    def _on_addr(self, key: str, raw: Optional[bytes]):
        import json

        inst = int(key.rsplit("/", 1)[-1], 16)
        if raw is None:
            self._addrs.pop(inst, None)
            for owners in self._owners.values():
                owners.discard(inst)
            return
        try:
            self._addrs[inst] = json.loads(raw)["addr"]
        except Exception:  # noqa: BLE001
            logger.warning("bad data plane advertisement %s", key)

    async def _addr_loop(self, watch):
        async for event in watch:
            self._on_addr(event.key, event.value if event.type == "put" else None)

    async def _mirror_loop(self):
        from ..runtime import codec

        async for payload in self._sub:
            try:
                msg = codec.unpack(payload)
                inst = int(msg["worker"])
                if inst == self.instance_id:
                    continue
                if msg["op"] == "stored":
                    for h in msg["hashes"]:
                        self._owners.setdefault(int(h), set()).add(inst)
                elif msg["op"] == "cleared":
                    for owners in self._owners.values():
                        owners.discard(inst)
                elif msg["op"] == "sync_request":
                    # a late joiner asked for the mesh state: re-announce
                    # everything our tiers hold
                    held = self.manager.all_hashes()
                    if held:
                        self.announce("stored", held)
            except Exception:  # noqa: BLE001
                logger.exception("bad kvbm announcement")

    def announce_threadsafe(self, op: str, hashes: Sequence[int]):
        """Schedule an announcement from any thread (offloads run on the
        engine's device-exec thread)."""
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self.announce, op, list(hashes))

    def announce(self, op: str, hashes: Sequence[int]):
        """Fire-and-forget announcement of our tier contents."""
        from ..runtime import codec

        if self.drt.discovery is None:
            return

        async def _pub():
            try:
                await self.drt.discovery.publish(
                    self.topic,
                    codec.pack(
                        {"worker": self.instance_id, "op": op,
                         "hashes": [int(h) for h in hashes]}
                    ),
                )
            except Exception:  # noqa: BLE001 — announcements are best-effort
                logger.debug("kvbm announce failed", exc_info=True)

        t = asyncio.get_running_loop().create_task(_pub())
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    # -- probe/pull (G4 role) ------------------------------------------- #

    def remote_owner(self, h: int) -> Optional[Tuple[int, str]]:
        for inst in self._owners.get(int(h), ()):  # first live owner wins
            addr = self._addrs.get(inst)
            if addr:
                return inst, addr
        return None

    def extend_prefix(self, hashes: Sequence[int]) -> List[int]:
        """Longest leading run of `hashes` available remotely."""
        out: List[int] = []
        for h in hashes:
            if self.remote_owner(h) is None:
                break
            out.append(int(h))
        return out

    async def pull_blocks(
        self, hashes: Sequence[int]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Fetch blocks from peers ([n, *block_shape] stacks), grouping by
        owner; raises KeyError when any block has no reachable owner."""
        from ..llm.kv_transfer import pull_kvbm_blocks

        plan: Dict[str, List[int]] = {}
        for h in hashes:
            owner = self.remote_owner(h)
            if owner is None:
                raise KeyError(f"kvbm block {h} has no remote owner")
            plan.setdefault(owner[1], []).append(int(h))
        parts: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for addr, hs in plan.items():
            k, v = await pull_kvbm_blocks(
                addr, hs, self.manager.block_shape, self.manager.dtype
            )
            for i, h in enumerate(hs):
                parts[h] = (k[i], v[i])
            self.remote_blocks_pulled += len(hs)
        self.remote_onboards += 1
        ks = np.stack([parts[int(h)][0] for h in hashes])
        vs = np.stack([parts[int(h)][1] for h in hashes])
        return ks, vs

    def stats(self) -> dict:
        return {
            "kvbm_remote_onboards": self.remote_onboards,
            "kvbm_remote_blocks_pulled": self.remote_blocks_pulled,
            "kvbm_known_remote_blocks": sum(
                1 for owners in self._owners.values() if owners
            ),
        }

    async def close(self):
        # in-flight best-effort announcements die with the mirror
        for t in list(self._bg):
            t.cancel()
        if self._task:
            self._task.cancel()
        if self._addr_task:
            self._addr_task.cancel()
        if self._sub:
            await self._sub.cancel()
