"""Distributed KVBM: cross-worker block sharing over the KV data plane.

The reference runs a KVBM leader/worker pair over ZMQ so one worker can
onboard blocks another worker offloaded (block_manager/distributed/
leader.rs:126, worker.rs:137) — the disagg-adjacent reuse that makes a
decode worker's admission hit on a prefill worker's offloaded prefix.

TPU-native redesign (no leader): a symmetric announcement mesh.
  * every KVBM-enabled worker announces stored/cleared block hashes on a
    discovery topic (kvbm_blocks/{ns}/{comp}) and serves block reads on
    its existing KV data plane (llm/kv_transfer.py; the server resolves
    `{"blocks": [...]}` handshakes straight from the tier manager).
  * every worker mirrors the announcements into hash -> {instance} plus
    the peers' data-plane addresses (DATA_PLANE_ROOT entries), so an
    admission probe extends the local tier prefix with remote hits at
    in-memory cost.
  * onboarding pulls the missing blocks point-to-point from ONE owner and
    write-throughs them into the local host tier (promotion), so repeat
    hits are local.

The remote-peer pool IS this build's G4 tier (reference CacheLevel G4,
block_manager.rs:63): same probe/onboard interface as G2/G3, backed by
another worker's memory instead of object storage.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

logger = logging.getLogger(__name__)

KVBM_TOPIC_FMT = "kvbm_blocks/{namespace}/{component}"


class KvbmDistributed:
    """The announcement mesh + remote pull for one worker's KVBM."""

    def __init__(
        self,
        drt,
        connector,  # kvbm.manager.KvbmConnector
        data_plane,  # llm.kv_transfer.KvDataPlaneServer (serves our blocks)
        namespace: str,
        component: str,
        instance_id: int,
    ):
        self.drt = drt
        self.connector = connector
        self.manager = connector.manager
        self.data_plane = data_plane
        self.topic = KVBM_TOPIC_FMT.format(namespace=namespace, component=component)
        self.instance_id = instance_id
        # hash -> instances that announced it; instance -> data plane addr
        self._owners: Dict[int, Set[int]] = {}
        self._addrs: Dict[int, str] = {}
        self._sub = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._task: Optional[asyncio.Task] = None
        self._addr_task: Optional[asyncio.Task] = None
        self._drain_task: Optional[asyncio.Task] = None
        self._bg: set = set()
        self.remote_onboards = 0
        self.remote_blocks_pulled = 0
        self.remote_bytes_pulled = 0
        self.remote_pull_failures = 0
        # per-peer transfer-rate EWMA (ms per block, keyed by data-plane
        # addr): the third arm of the onboard cost model — peer-pull vs
        # local-tier vs recompute (docs/kvbm.md cluster KV fabric). None
        # until a pull is observed: a cold peer never defers an onboard,
        # the same rule the local tiers and the scheduler CostModel use.
        self._pull_ms_per_block: Dict[str, float] = {}
        # session checkpointing (kvbm/checkpoint.py, DYN_KV_CHECKPOINT):
        # replicates committed session blocks to a peer's G2 so a worker
        # death loses only the un-checkpointed tail. None when off.
        self.checkpointer = None
        self._ckpt_task: Optional[asyncio.Task] = None
        # hashes known to be checkpoint REPLICAS (pushed into our tiers by
        # a peer, or mesh-announced as checkpointed anywhere): the
        # engine's resume-source classifier reads this. Bounded.
        self._ckpt_hashes: set = set()
        # fast corpse cleanup (docs/fault_tolerance.md): peers whose
        # data plane failed us get quarantined until this deadline — the
        # onboard budget and the checkpointer stop dialing a corpse
        # instead of paying the connect-timeout tax per admission. Any
        # fresh announcement from the instance lifts the quarantine
        # early; lease expiry (addr delete) removes it entirely.
        self._dead: Dict[int, float] = {}
        # peers that REFUSED a checkpoint push for a structural reason
        # (no kvbm tier — a tier-less prefill worker still advertises its
        # data plane — or a kv_format mismatch): unlike a transport
        # failure these do not heal with time, so a TTL quarantine would
        # re-select the same broken ring successor every ~30s and drop a
        # batch (plus poison its chain) per cycle, forever. Durable for
        # the instance's lease lifetime; lease expiry (addr delete)
        # removes the entry, and a restarted worker gets a fresh id.
        self._ckpt_ineligible: Set[int] = set()
        # peer pull latency histogram (ms per pull_blocks call)
        self._pull_hist_bounds = (5.0, 20.0, 50.0, 100.0, 250.0, 1000.0)
        self._pull_hist = [0] * (len(self._pull_hist_bounds) + 1)
        self._pull_ms_sum = 0.0
        # serve our tier blocks on the data plane; the back-pointer lets
        # the server's checkpoint-receive path tag + announce replicas
        if data_plane is not None:
            data_plane.kvbm_source = self.manager
            data_plane.kvbm_distributed = self
        connector.distributed = self

    async def start(self):
        from ..llm.kv_transfer import DATA_PLANE_ROOT

        self._loop = asyncio.get_running_loop()
        if self.drt.discovery is None:
            return
        self._sub = await self.drt.discovery.subscribe(self.topic)
        self._task = asyncio.create_task(self._mirror_loop())
        # periodic eviction-retraction drain: the connector announces
        # drops inline on its own store/load paths, but a worker that
        # mostly SERVES peer pulls (data-plane promotions cascade drops
        # with no connector involvement) needs this sweep or peers keep
        # stale owners indefinitely
        self._drain_task = asyncio.create_task(self._drain_evictions_loop())
        watch = await self.drt.discovery.watch_prefix(DATA_PLANE_ROOT)
        for item in watch.snapshot:
            self._on_addr(item["key"], item["value"])
        self._addr_task = asyncio.create_task(self._addr_loop(watch))
        from .checkpoint import KvCheckpointer, checkpoint_queue_blocks

        ckpt_blocks = checkpoint_queue_blocks()
        if ckpt_blocks > 0:
            self.checkpointer = KvCheckpointer(self, ckpt_blocks)
            self._ckpt_task = asyncio.create_task(self.checkpointer.run())
        # announcements are fire-and-forget pub/sub: a worker that joins
        # AFTER peers offloaded (fresh decode replica, post-crash restart)
        # would never learn their tier contents — ask everyone to
        # re-announce (peers reply with their full hash sets)
        self.announce("sync_request", [])

    def _on_addr(self, key: str, raw: Optional[bytes]):
        import json

        inst = int(key.rsplit("/", 1)[-1], 16)
        if raw is None:
            self._addrs.pop(inst, None)
            self._ckpt_ineligible.discard(inst)
            self._drop_owner(inst, None)
            return
        try:
            self._addrs[inst] = json.loads(raw)["addr"]
        except Exception:  # noqa: BLE001
            logger.warning("bad data plane advertisement %s", key)

    async def _addr_loop(self, watch):
        async for event in watch:
            self._on_addr(event.key, event.value if event.type == "put" else None)

    async def _drain_evictions_loop(self):
        while True:
            await asyncio.sleep(2.0)
            evicted = self.manager.drain_evicted()
            if evicted:
                self.announce("evicted", evicted)

    async def _mirror_loop(self):
        from ..runtime import codec

        async for payload in self._sub:
            try:
                msg = codec.unpack(payload)
                inst = int(msg["worker"])
                if inst == self.instance_id:
                    continue
                # a live announcement lifts any failure quarantine early —
                # the peer is demonstrably back (restart, transient net)
                self._dead.pop(inst, None)
                if msg["op"] == "stored":
                    for h in msg["hashes"]:
                        self._owners.setdefault(int(h), set()).add(inst)
                elif msg["op"] == "checkpoint":
                    # session-checkpoint replicas: owners like `stored`,
                    # plus the hash is tagged so a survivor's resume
                    # classifies as checkpoint-assisted
                    for h in msg["hashes"]:
                        self._owners.setdefault(int(h), set()).add(inst)
                        self._tag_checkpoint(int(h))
                elif msg["op"] == "evicted":
                    # the peer's tiers dropped these blocks entirely
                    # (bounded tiers / bounded index churn): forget the
                    # owner so probes stop extending onto a dead entry
                    self._drop_owner(inst, msg["hashes"])
                elif msg["op"] == "cleared":
                    self._drop_owner(inst, None)
                elif msg["op"] == "sync":
                    # full-set re-announcement (sync_request reply, worker
                    # restart): REPLACE the peer's owner set. A union here
                    # would resurrect hashes the peer evicted between its
                    # announcements — exactly the stale-owner bug a capped
                    # index plus worker churn exposes.
                    self._drop_owner(inst, None)
                    for h in msg["hashes"]:
                        self._owners.setdefault(int(h), set()).add(inst)
                elif msg["op"] == "sync_request":
                    self._answer_sync()
            except Exception:  # noqa: BLE001
                logger.exception("bad kvbm announcement")

    def _answer_sync(self):
        """Answer a late joiner's sync_request: re-announce everything our
        tiers hold as a replace-set (so the joiner can't inherit stale
        entries), then re-tag the checkpoint replicas among them — the
        `sync` op alone would leave the joiner classifying resumes served
        by those replicas as `peer` instead of `checkpoint`."""
        all_hashes = [int(h) for h in self.manager.all_hashes()]
        self.announce("sync", all_hashes)
        ck = [h for h in all_hashes if h in self._ckpt_hashes]
        if ck:
            self.announce("checkpoint", ck)

    def _drop_owner(self, inst: int, hashes: Optional[Sequence[int]]):
        """Remove `inst` as owner of `hashes` (None = everywhere), pruning
        empty entries so _owners stays bounded by live mesh contents."""
        keys = (
            [int(h) for h in hashes] if hashes is not None
            else list(self._owners.keys())
        )
        for h in keys:
            owners = self._owners.get(h)
            if owners is None:
                continue
            owners.discard(inst)
            if not owners:
                del self._owners[h]

    def announce_threadsafe(self, op: str, hashes: Sequence[int]):
        """Schedule an announcement from any thread (offloads run on the
        engine's device-exec thread)."""
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self.announce, op, list(hashes))

    def announce(self, op: str, hashes: Sequence[int]):
        """Fire-and-forget announcement of our tier contents."""
        from ..runtime import codec

        if self.drt.discovery is None:
            return

        async def _pub():
            try:
                await self.drt.discovery.publish(
                    self.topic,
                    codec.pack(
                        {"worker": self.instance_id, "op": op,
                         "hashes": [int(h) for h in hashes]}
                    ),
                )
            except Exception:  # noqa: BLE001 — announcements are best-effort
                logger.debug("kvbm announce failed", exc_info=True)

        t = asyncio.get_running_loop().create_task(_pub())
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    # -- corpse quarantine + checkpoint tags ----------------------------- #

    def note_peer_failure(self, inst: int, ttl_s: float = 30.0):
        """A pull/push to this peer's data plane failed: quarantine it so
        the onboard budget and checkpointer stop dialing the corpse (fast
        corpse cleanup). Lifted early by any fresh announcement from the
        instance; the addr-delete at lease expiry is the authority."""
        self._dead[int(inst)] = time.monotonic() + ttl_s

    def note_checkpoint_ineligible(self, inst: int):
        """This peer refused a checkpoint push for a STRUCTURAL reason
        (no kvbm tier, kv_format mismatch): exclude it from checkpoint
        peer selection for as long as it advertises this instance id —
        the ring would otherwise re-pick the same broken successor at
        every quarantine expiry and shed a batch per cycle. Pull/onboard
        roles are untouched (a tier-less worker still serves streamed
        handoffs)."""
        if len(self._ckpt_ineligible) >= 1024:
            # bounded; entries normally leave via addr-delete, so this
            # only trips under pathological id churn without leases
            self._ckpt_ineligible.pop()
        self._ckpt_ineligible.add(int(inst))

    def _quarantined(self, inst: int) -> bool:
        dl = self._dead.get(int(inst))
        if dl is None:
            return False
        if time.monotonic() >= dl:
            del self._dead[int(inst)]
            return False
        return True

    def _tag_checkpoint(self, h: int):
        if len(self._ckpt_hashes) >= 65536:
            # bounded: drop an arbitrary half — tags are an observability
            # refinement (resume classifies as `peer` without one), so a
            # coarse trim never affects correctness
            for _ in range(32768):
                self._ckpt_hashes.pop()
        self._ckpt_hashes.add(int(h))

    def note_checkpoint_received(self, hashes: Sequence[int]):
        """The data plane stored a peer's checkpoint push into OUR tiers:
        tag the hashes locally and announce them as `checkpoint` so the
        rest of the mesh (including the original owner's survivors) can
        route resumes here."""
        for h in hashes:
            self._tag_checkpoint(int(h))
        self.announce("checkpoint", list(hashes))

    def any_checkpoint(self, hashes: Sequence[int]) -> bool:
        return any(int(h) in self._ckpt_hashes for h in hashes)

    def checkpoint_peer(self) -> Optional[Tuple[int, str]]:
        """The replication target: the ring successor — the first live,
        non-quarantined peer with an advertised data plane whose id
        follows this worker's (wrapping). Stable across calls so one
        session's blocks land on ONE peer (a scattered prefix would cost
        the survivor a pull per peer), and per-WORKER distinct so the
        fleet's replication load spreads instead of concentrating every
        worker's checkpoint stream on the lowest-id peer (whose G2 would
        churn under (N-1)x write load and whose death would take every
        session replica with it)."""
        me = self.instance_id
        ring = sorted(self._addrs)
        for inst in [i for i in ring if i > me] + [i for i in ring if i < me]:
            if self._quarantined(inst) or inst in self._ckpt_ineligible:
                continue
            addr = self._addrs.get(inst)
            if addr:
                return inst, addr
        return None

    # -- probe/pull (G4 role) ------------------------------------------- #

    def remote_owner(
        self, h: int, hint_instance: Optional[int] = None
    ) -> Optional[Tuple[int, str]]:
        """First live announced owner; `hint_instance` (the router-supplied
        holder from KvPushRouter's radix index) is the fallback when the
        announcement mesh hasn't mirrored the hash — the pull itself
        verifies, a wrong hint is just a KeyError fallback. Quarantined
        peers (recent data-plane failure) are skipped in both roles."""
        for inst in self._owners.get(int(h), ()):  # first live owner wins
            if self._quarantined(inst):
                continue
            addr = self._addrs.get(inst)
            if addr:
                return inst, addr
        if hint_instance is not None and not self._quarantined(int(hint_instance)):
            addr = self._addrs.get(int(hint_instance))
            if addr:
                return int(hint_instance), addr
        return None

    def extend_prefix(
        self, hashes: Sequence[int], hint_instance: Optional[int] = None,
        hint_blocks: int = 0,
    ) -> List[int]:
        """Longest leading run of `hashes` available remotely. The router
        hint covers the first `hint_blocks` entries of THIS slice."""
        out: List[int] = []
        for i, h in enumerate(hashes):
            hint = hint_instance if i < hint_blocks else None
            if self.remote_owner(h, hint_instance=hint) is None:
                break
            out.append(int(h))
        return out

    def estimate_pull_ms(
        self, hashes: Sequence[int], hint_instance: Optional[int] = None
    ) -> Optional[float]:
        """Projected peer-pull latency for `hashes` from the per-peer
        transfer-rate EWMAs. Pulls from distinct peers run concurrently
        (pull_blocks gathers), so the projection is the MAX over peers of
        that peer's span, not the sum. None when any needed peer has
        never been observed (cold peers never defer an onboard) or a
        hash has no reachable owner."""
        per_peer: Dict[str, float] = {}
        for h in hashes:
            owner = self.remote_owner(h, hint_instance=hint_instance)
            if owner is None:
                return None
            ms = self._pull_ms_per_block.get(owner[1])
            if ms is None:
                return None
            per_peer[owner[1]] = per_peer.get(owner[1], 0.0) + ms
        return max(per_peer.values(), default=0.0)

    async def pull_blocks(
        self, hashes: Sequence[int], hint_instance: Optional[int] = None
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Fetch blocks from peers ([n, *block_shape] stacks), grouping by
        owner; raises KeyError when any block has no reachable owner and
        KvTransferError when a peer fails mid-pull (both convert to
        recompute in the onboard path). Observes per-peer transfer rate."""
        from ..llm.kv_transfer import pull_kvbm_blocks

        plan: Dict[str, List[int]] = {}
        addr_inst: Dict[str, int] = {}
        for h in hashes:
            owner = self.remote_owner(h, hint_instance=hint_instance)
            if owner is None:
                raise KeyError(f"kvbm block {h} has no remote owner")
            plan.setdefault(owner[1], []).append(int(h))
            addr_inst[owner[1]] = owner[0]
        t0 = time.perf_counter()
        parts: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}

        async def pull_one(addr: str, hs: List[int]):
            t_peer = time.perf_counter()
            try:
                # tight connect budget: this is the admission/TTFT
                # critical path and a dead peer must cost a bounded
                # fallback-to-recompute, not a 10s dial
                k, v = await pull_kvbm_blocks(
                    addr, hs, self.manager.block_shape, self.manager.dtype,
                    kv_format=self.manager.kv_format, connect_timeout=2.0,
                )
            except (KeyError, asyncio.CancelledError):
                raise  # block miss / teardown: the peer itself is fine
            except BaseException:
                # transport failure: quarantine so the NEXT admission's
                # onboard budget skips this peer instead of re-paying the
                # connect-timeout tax on a corpse (fast corpse cleanup)
                self.note_peer_failure(addr_inst.get(addr, -1))
                raise
            ms = (time.perf_counter() - t_peer) * 1000.0
            prev = self._pull_ms_per_block.get(addr)
            per_block = ms / max(len(hs), 1)
            self._pull_ms_per_block[addr] = (
                per_block if prev is None else 0.8 * prev + 0.2 * per_block
            )
            for i, h in enumerate(hs):
                parts[h] = (k[i], v[i])
            self.remote_blocks_pulled += len(hs)
            self.remote_bytes_pulled += int(k.nbytes) + int(v.nbytes)

        # independent peers pull CONCURRENTLY: this is admission/TTFT
        # critical path, and a prefix split across N owners (worker
        # churn) must cost max(per-peer), not the sum. The whole gather is
        # one onboard attempt: the FIRST failure dooms it (the caller
        # falls back to recompute), so siblings are cancelled and drained
        # — not left racing to fill `parts` nobody will read — and the
        # attempt counts as ONE typed failure however many peers it hit.
        tasks = [
            asyncio.create_task(pull_one(addr, hs))
            for addr, hs in plan.items()
        ]
        async def _reap_siblings():
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

        try:
            await asyncio.gather(*tasks)
        except asyncio.CancelledError:
            # the ONBOARD was cancelled (slot abort/teardown), no peer
            # failed: clean the siblings up without charging a failure
            await asyncio.shield(_reap_siblings())
            raise
        except BaseException:
            await asyncio.shield(_reap_siblings())
            self.remote_pull_failures += 1
            raise
        self.remote_onboards += 1
        total_ms = (time.perf_counter() - t0) * 1000.0
        self._pull_ms_sum += total_ms
        for i, bound in enumerate(self._pull_hist_bounds):
            if total_ms <= bound:
                self._pull_hist[i] += 1
                break
        else:
            self._pull_hist[-1] += 1
        ks = np.stack([parts[int(h)][0] for h in hashes])
        vs = np.stack([parts[int(h)][1] for h in hashes])
        return ks, vs

    def stats(self) -> dict:
        out = {
            "kvbm_remote_onboards": self.remote_onboards,
            "kvbm_remote_blocks_pulled": self.remote_blocks_pulled,
            "kvbm_peer_bytes_pulled": self.remote_bytes_pulled,
            "kvbm_peer_pull_failures": self.remote_pull_failures,
            "kvbm_peer_pull_ms_sum": round(self._pull_ms_sum, 3),
            "kvbm_peer_pull_hist": {
                **{
                    f"le_{b:g}ms": n
                    for b, n in zip(self._pull_hist_bounds, self._pull_hist)
                },
                "inf": self._pull_hist[-1],
            },
            "kvbm_known_remote_blocks": sum(
                1 for owners in self._owners.values() if owners
            ),
            "kvbm_quarantined_peers": sum(
                1 for i in list(self._dead) if self._quarantined(i)
            ),
            "kvbm_known_checkpoint_blocks": len(self._ckpt_hashes),
            "kvbm_ckpt_ineligible_peers": len(self._ckpt_ineligible),
        }
        if self.checkpointer is not None:
            out.update(self.checkpointer.stats())
        for addr, ms in self._pull_ms_per_block.items():
            out.setdefault("kvbm_peer_ms_per_block", {})[addr] = round(ms, 3)
        return out

    async def close(self):
        # in-flight best-effort announcements die with the mirror
        for t in list(self._bg):
            t.cancel()
        if self.checkpointer is not None:
            self.checkpointer.close()
        if self._ckpt_task:
            self._ckpt_task.cancel()
        if self._task:
            self._task.cancel()
        if self._addr_task:
            self._addr_task.cancel()
        if self._drain_task:
            self._drain_task.cancel()
        if self._sub:
            await self._sub.cancel()
