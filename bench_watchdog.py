"""Unattended opportunistic TPU measurement (round-4 verdict next #1).

Rounds 3 and 4 lost their entire hardware-measurement windows to axon
tunnel outages because the bench ladder only ran when invoked. This
watchdog runs ALL round: it probes the backend on a short interval and,
whenever the tunnel is up, advances the measurement ladder one phase at a
time, appending every result line to `bench_tpu_results.jsonl` as valid
JSONL (notes are {"note": ...} records, never bare comments — round-4
advisor low #4).

Robustness model (from the round-4 ladder post-mortem):
  * per-phase rc comes from the benchmark process itself, not a pipeline
    tail (`subprocess.run`, no shell);
  * a cooldown between phases lets the tunnel server release the previous
    client's HBM (the r4 back-to-back RESOURCE_EXHAUSTED signature);
  * failed phases are retried up to MAX_ATTEMPTS on later probes, state
    persists in `bench_watchdog_state.json` so a watchdog restart resumes
    instead of redoing finished work;
  * once every phase is ok (or exhausted) the watchdog exits, freeing the
    chip for the driver's end-of-round bench.py run.

Usage:  nohup python bench_watchdog.py > bench_watchdog.log 2>&1 &
        python bench_watchdog.py --once          # single pass, no loop
        python bench_watchdog.py --mark-ok e2e_agg   # seed state
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
STATE = REPO / "bench_watchdog_state.json"
OUT = REPO / "bench_tpu_results.jsonl"

PROBE_INTERVAL_S = 240.0  # tunnel-down re-probe cadence
COOLDOWN_S = 45.0  # post-phase pause: tunnel-side HBM release
MAX_ATTEMPTS = 3

# ladder: conservative configs first (int8 + fixed pools dodge the 16 GiB
# single-chip OOMs that killed half the round-4 ladder), the north-star
# e2e number before anything else.
PY = sys.executable
PHASES = [
    # (name, argv, timeout_s)
    ("e2e_agg", [PY, "bench_e2e.py", "--mode", "agg", "--quantize", "int8",
                 "--num-pages", "512"], 2400),
    ("raw_bf16", [PY, "bench.py", "--raw"], 1800),
    ("engine_bf16", [PY, "bench_engine.py"], 1800),
    ("raw_int8", [PY, "bench.py", "--raw", "--quantize", "int8"], 1800),
    ("engine_int8", [PY, "bench_engine.py", "--quantize", "int8"], 1800),
    ("ttft", [PY, "bench_ttft.py"], 1200),
    ("sweep", [PY, "bench_sweep.py", "--quick", "--out", "sweep_tpu.json"],
     5400),
    ("e2e_agg_bf16", [PY, "bench_e2e.py", "--mode", "agg", "--num-pages",
                      "384"], 2400),
    ("disagg", [PY, "bench_e2e.py", "--mode", "disagg", "--quantize", "int8"],
     3600),
    ("kv_benefit", [PY, "bench_e2e.py", "--mode", "kv", "--prefix-ratio",
                    "0.5", "--router-compare", "--quantize", "int8"], 5400),
    ("kv_trace", [PY, "bench_e2e.py", "--mode", "kv", "--trace", "synth",
                  "--trace-speedup", "4", "--router-compare",
                  "--quantize", "int8"], 5400),
    ("spec_decode", [PY, "bench_engine.py", "--quantize", "int8",
                     "--spec", "ngram"], 1800),
    # PR 8 remeasure: unified-vs-split mixed dispatch on real hardware
    # (CPU interpreter-mode numbers in BENCH_NOTES_r07.md; the step-time
    # split only means anything where the Pallas kernel actually runs) —
    # pre-PR-8 phases are seeded ok in bench_watchdog_state.json so a
    # watchdog restart runs just this phase
    ("engine_mixed", [PY, "bench_engine.py", "--mixed", "--quantize",
                      "int8"], 2400),
    # PR 10 remeasure: KVBM tier pipeline on real hardware — where the
    # XLA gather dispatch is actually async, so the batched-offload
    # device-µs split (CPU numbers in BENCH_NOTES_r08.md are
    # synchronous-execution artifacts) and the onboard-vs-recompute TTFT
    # gap mean something
    ("engine_kv", [PY, "bench_kv_cache.py", "--repeat", "2", "--requests",
                   "64", "--quantize", "int8", "--num-pages", "512",
                   "--host-blocks", "1024", "--disk-blocks", "512"], 3600),
    # PR 11 remeasure: cluster KV fabric on real hardware — cross-worker
    # warm TTFT (peer G2 pull over the data plane) vs local-G2 onboard vs
    # recompute, where the transfer actually crosses a NIC instead of
    # loopback (CPU medians in BENCH_NOTES_r09.md)
    ("engine_peer", [PY, "bench_kv_cache.py", "--multi-worker", "--requests",
                     "64", "--quantize", "int8", "--num-pages", "512",
                     "--host-blocks", "1024"], 3600),
    # PR 14 remeasure: quantized KV cache on real hardware — sessions-per-
    # HBM at the real pool auto-sizing (the CPU arm measures a fixed tiny
    # pool), the in-kernel VMEM-window dequant cost inside the compiled
    # Mosaic ragged/decode kernels (interpret-mode CPU numbers say nothing
    # about it), and the quality guard on a real checkpoint's peaked
    # logits (the random-init tiny model is the worst case)
    ("engine_kvq", [PY, "bench_kv_cache.py", "--kv-quant", "int8",
                    "--requests", "64", "--num-pages", "512",
                    "--quantize", "int8"], 3600),
    # PR 13 remeasure: frontend fleet scale-out on the many-core TPU host
    # — the 1→2→4 frontend tok/s ladder at 32 streams (plus the codec A/B
    # riding --fleet's per-arm CPU columns) is core-bound on the 2-core
    # dev box (BENCH_NOTES_r10.md), so the near-linear claim needs a host
    # where 4 frontends + worker + client actually get their own cores
    ("engine_fleet", [PY, "bench_serving_overhead.py", "--fleet",
                      "--streams", "32", "--osl", "96"], 1800),
    # PR 15 remeasure: durable decode sessions on real hardware — the
    # checkpoint-resume vs recompute-resume TTFT gap where the session
    # prefix actually crosses a NIC into the peer's G2 and the survivor's
    # onboard pays real transfer+inject instead of loopback memcpy (CPU
    # medians: 12.6ms vs 29.3ms at 512-token sessions, ratio 0.43)
    ("engine_migration", [PY, "bench_migration.py", "--decode", "448",
                          "--rounds", "5", "--max-ratio", "0.5",
                          "--smoke"], 1800),
    # PR 18 remeasure: live role morphing on real hardware — the
    # phase-flip soak (morph arm vs cold-spawn time-to-recovery, plus the
    # worker.morph error/crash chaos variants) where the re-warm of the
    # incoming role's compile surfaces costs real XLA compiles instead of
    # the mocker's free flip, so the morph-vs-spawn pricing gap is the
    # honest one
    ("engine_morph", [PY, "-m", "pytest", "tests/test_planner_soak.py",
                      "-q", "-k", "morph_soak", "-p", "no:cacheprovider",
                      "-p", "no:xdist", "-p", "no:randomly"], 1800),
    # PR 19 remeasure: blended guided+LoRA+spec traffic fused onto the
    # unified ragged dispatch on real hardware — the tokens/dispatch
    # fused-vs-split gap where the variant operands (packed FSM mask +
    # per-row adapter gather) run inside the compiled Mosaic kernel
    # instead of interpret mode (CPU numbers in BENCH_NOTES: 6.8 vs 2.8)
    ("engine_blend", [PY, "bench_engine.py", "--mixed", "--blend",
                      "guided:lora:spec", "--quantize", "int8"], 2400),
    # PR 19 remeasure: adapter paging at fleet scale on real hardware —
    # the hot-switch acquire (device stack already resident, should stay
    # ~0) vs cold-onboard EWMA where the LoRA page actually crosses
    # host->HBM instead of a loopback memcpy, at adapters >> pool slots
    ("engine_lora", [PY, "bench_serving_overhead.py", "--lora-sweep",
                     "--lora-adapters", "8", "--lora-slots", "3"], 1800),
]


def log(msg: str):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", flush=True)


def append_jsonl(record: dict):
    with OUT.open("a") as f:
        f.write(json.dumps(record) + "\n")


def load_state() -> dict:
    if STATE.exists():
        try:
            return json.loads(STATE.read_text())
        except ValueError:
            pass
    return {}


def save_state(state: dict):
    STATE.write_text(json.dumps(state, indent=1))


def probe(deadline: float = 90.0) -> bool:
    code = "import jax; d = jax.devices(); print(d[0].platform)"
    try:
        r = subprocess.run([PY, "-c", code], capture_output=True, text=True,
                           timeout=deadline)
    except subprocess.TimeoutExpired:
        return False
    return r.returncode == 0 and "tpu" in (r.stdout or "")


def run_phase(name: str, argv: list, timeout: float) -> int:
    log(f"phase {name}: {' '.join(argv[1:])}")
    append_jsonl({"note": f"phase {name} start",
                  "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())})
    env = dict(os.environ, DYN_BENCH_SKIP_PROBE="1")
    t0 = time.time()
    try:
        r = subprocess.run(argv, capture_output=True, text=True,
                           timeout=timeout, env=env, cwd=str(REPO))
        rc = r.returncode
        stdout, stderr = r.stdout or "", r.stderr or ""
    except subprocess.TimeoutExpired as e:
        rc, stdout = 124, (e.stdout or b"").decode("utf-8", "replace") \
            if isinstance(e.stdout, bytes) else (e.stdout or "")
        stderr = ""
    n_lines = 0
    for line in stdout.splitlines():
        if line.startswith("{"):
            try:
                append_jsonl({"phase": name, **json.loads(line)})
                n_lines += 1
            except ValueError:
                pass
    tail = stderr.strip().splitlines()[-3:]
    append_jsonl({"note": f"phase {name} done", "rc": rc,
                  "wall_s": round(time.time() - t0, 1), "json_lines": n_lines,
                  **({"stderr_tail": " | ".join(tail)} if rc != 0 else {})})
    log(f"phase {name} rc={rc} ({n_lines} result lines, "
        f"{time.time() - t0:.0f}s)")
    return rc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--once", action="store_true",
                    help="one probe+phase pass, then exit")
    ap.add_argument("--mark-ok", action="append", default=[],
                    help="seed a phase as already measured")
    args = ap.parse_args()

    state = load_state()
    for name in args.mark_ok:
        state[name] = {"status": "ok", "attempts": 0, "seeded": True}
        save_state(state)
        log(f"seeded {name}=ok")
    if args.mark_ok and not args.once:
        return 0

    log(f"watchdog up; ladder = {[p[0] for p in PHASES]}")
    while True:
        pending = [
            (n, a, t) for n, a, t in PHASES
            if state.get(n, {}).get("status") != "ok"
            and state.get(n, {}).get("attempts", 0) < MAX_ATTEMPTS
        ]
        if not pending:
            log("ladder complete (all phases ok or exhausted); exiting")
            append_jsonl({"note": "watchdog ladder complete",
                          "state": {k: v.get("status") for k, v in
                                    state.items()}})
            return 0
        if not probe():
            log(f"tunnel down; {len(pending)} phases pending; "
                f"sleeping {PROBE_INTERVAL_S:.0f}s")
            if args.once:
                return 1
            time.sleep(PROBE_INTERVAL_S)
            continue
        name, argv, timeout = pending[0]
        rc = run_phase(name, argv, timeout)
        st = state.setdefault(name, {"attempts": 0})
        st["attempts"] = st.get("attempts", 0) + 1
        st["status"] = "ok" if rc == 0 else "failed"
        st["rc"] = rc
        save_state(state)
        if args.once:
            return 0
        time.sleep(COOLDOWN_S)


if __name__ == "__main__":
    sys.exit(main())
