"""Decode KV-write strategy sweep (round-3 verdict #2).

Measures engine-path decode across (pool_mode, unroll, num_pages) to pick
the production default for EngineConfig.decode_pool_mode at >=1024-page
pools. Each configuration runs in a fresh subprocess (one engine per
process; donated buffers make in-process re-runs unsafe) and the
persistent XLA compile cache (engine._enable_compile_cache) amortizes the
Mosaic compiles across them, so only the first run of each program shape
pays the 20-40s compile.

Usage: python bench_sweep.py [--quick] [--out sweep.json]
Prints one JSON line per configuration plus a final summary with the
winning mode per pool size.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent


def run_cfg(pool_mode: str, unroll: int, num_pages: int, *, batch: int,
            osl: int, timeout: float) -> dict:
    cmd = [
        sys.executable, str(REPO / "bench_engine.py"),
        "--pool-mode", pool_mode, "--unroll", str(unroll),
        "--num-pages", str(num_pages),
        "--batch", str(batch), "--osl", str(osl), "--churn-s", "0",
    ]
    t0 = time.time()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"pool_mode": pool_mode, "unroll": unroll,
                "num_pages": num_pages, "error": "timeout"}
    line = None
    for ln in (r.stdout or "").splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            line = json.loads(ln)
    out = {"pool_mode": pool_mode, "unroll": unroll, "num_pages": num_pages,
           "wall_s": round(time.time() - t0, 1)}
    if line is None or r.returncode != 0:
        out["error"] = (r.stderr or "")[-400:] or f"rc={r.returncode}"
        return out
    if "error" in line:
        out["error"] = line["error"]
        return out
    out["decode_tok_s"] = line.get("value")
    out["itl_ms"] = line.get("itl_ms")
    # bench_engine floors the pool at the batch's working-set need; record
    # what actually ran so rows are never mislabeled
    out["num_pages_effective"] = line.get("num_pages", num_pages)
    return out


def run_sla_cfg(qps: float, ttft_ms: float, itl_ms: float, *, smoke: bool,
                requests: int, timeout: float) -> dict:
    """One point on the SLA frontier: bench_e2e with the sla policy at
    (ttft, itl) targets and the given qps; rows carry attainment +
    throughput so BENCH_NOTES can chart the frontier."""
    cmd = [
        sys.executable, str(REPO / "bench_e2e.py"),
        *(["--smoke"] if smoke else []),
        "--qps", str(qps), "--requests", str(requests),
        "--sched-policy", "sla",
        "--ttft-slo-ms", str(ttft_ms), "--itl-slo-ms", str(itl_ms),
    ]
    out = {"qps": qps, "ttft_target_ms": ttft_ms, "itl_target_ms": itl_ms}
    t0 = time.time()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        out["error"] = "timeout"
        return out
    line = None
    for ln in (r.stdout or "").splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            line = json.loads(ln)
    out["wall_s"] = round(time.time() - t0, 1)
    if line is None:
        out["error"] = (r.stderr or "")[-400:] or f"rc={r.returncode}"
        return out
    out["output_tok_s"] = line.get("value")
    out["ttft_p50_ms"] = line.get("ttft_p50_ms")
    out["ttft_p99_ms"] = line.get("ttft_p99_ms")
    out["itl_p50_ms"] = line.get("itl_p50_ms")
    out["failed"] = line.get("failed")
    sla = line.get("sla") or {}
    out["ttft_attainment"] = sla.get("ttft_attainment")
    out["itl_attainment"] = sla.get("itl_attainment")
    out["goodput_tok_s"] = sla.get("goodput_tok_s")
    return out


def sla_sweep(args) -> int:
    """--sla axis: ttft/itl targets x qps -> attainment/throughput
    frontier (CPU-mocker-scale by default via --smoke-scale)."""
    if args.quick:
        qps_axis = [4.0, 8.0]
        targets = [(1000.0, 50.0), (2000.0, 100.0)]
    else:
        qps_axis = [2.0, 4.0, 8.0]
        targets = [(500.0, 25.0), (1000.0, 50.0), (2000.0, 100.0)]
    results = []
    for qps in qps_axis:
        for ttft_ms, itl_ms in targets:
            res = run_sla_cfg(
                qps, ttft_ms, itl_ms, smoke=args.smoke_scale,
                requests=args.requests, timeout=args.timeout,
            )
            results.append(res)
            print(json.dumps(res), flush=True)
    # frontier summary: per qps, the tightest target still attaining >=0.9
    summary = {}
    for qps in qps_axis:
        clean = [
            r for r in results
            if r["qps"] == qps and (r.get("ttft_attainment") or 0) >= 0.9
        ]
        if clean:
            best = min(clean, key=lambda r: r["ttft_target_ms"])
            summary[str(qps)] = {
                "tightest_ttft_ms": best["ttft_target_ms"],
                "ttft_attainment": best["ttft_attainment"],
                "output_tok_s": best["output_tok_s"],
                "goodput_tok_s": best["goodput_tok_s"],
            }
    print(json.dumps({"sla_sweep_summary": summary}), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(
            {"results": results, "summary": summary}, indent=2))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description="decode KV-write strategy sweep")
    ap.add_argument("--quick", action="store_true",
                    help="fewer points (scatter + local@unroll4, 1024/2048 pages)")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--osl", type=int, default=64)
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="per-configuration budget (first runs pay compiles)")
    ap.add_argument("--out", default=None, help="also write results to this file")
    ap.add_argument("--sla", action="store_true",
                    help="sweep the SLA frontier instead: ttft/itl targets "
                    "x qps through bench_e2e --sched-policy sla "
                    "(attainment + throughput per point)")
    ap.add_argument("--smoke-scale", action="store_true", default=True,
                    help="--sla: run bench_e2e at --smoke scale (CPU, tiny "
                    "model); use --no-smoke-scale on hardware")
    ap.add_argument("--no-smoke-scale", dest="smoke_scale",
                    action="store_false")
    ap.add_argument("--requests", type=int, default=32,
                    help="--sla: requests per point")
    args = ap.parse_args(argv)

    if args.sla:
        return sla_sweep(args)

    pools = [1024, 2048] if args.quick else [392, 1024, 2048]
    configs = []
    for np_ in pools:
        configs.append(("scatter", 1, np_))
        for u in ([4] if args.quick else [2, 4, 8, 16]):
            configs.append(("local", u, np_))

    results = []
    for mode, unroll, np_ in configs:
        res = run_cfg(mode, unroll, np_, batch=args.batch, osl=args.osl,
                      timeout=args.timeout)
        results.append(res)
        print(json.dumps(res), flush=True)

    # winner per pool size (highest decode tok/s among clean runs)
    summary = {}
    for np_ in pools:
        clean = [r for r in results if r["num_pages"] == np_ and "decode_tok_s" in r]
        if clean:
            best = max(clean, key=lambda r: r["decode_tok_s"])
            summary[str(np_)] = {
                "pool_mode": best["pool_mode"], "unroll": best["unroll"],
                "decode_tok_s": best["decode_tok_s"], "itl_ms": best["itl_ms"],
            }
    print(json.dumps({"sweep_summary": summary}), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(
            {"results": results, "summary": summary}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
