"""Decode KV-write strategy sweep (round-3 verdict #2).

Measures engine-path decode across (pool_mode, unroll, num_pages) to pick
the production default for EngineConfig.decode_pool_mode at >=1024-page
pools. Each configuration runs in a fresh subprocess (one engine per
process; donated buffers make in-process re-runs unsafe) and the
persistent XLA compile cache (engine._enable_compile_cache) amortizes the
Mosaic compiles across them, so only the first run of each program shape
pays the 20-40s compile.

Usage: python bench_sweep.py [--quick] [--out sweep.json]
Prints one JSON line per configuration plus a final summary with the
winning mode per pool size.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent


def run_cfg(pool_mode: str, unroll: int, num_pages: int, *, batch: int,
            osl: int, timeout: float) -> dict:
    cmd = [
        sys.executable, str(REPO / "bench_engine.py"),
        "--pool-mode", pool_mode, "--unroll", str(unroll),
        "--num-pages", str(num_pages),
        "--batch", str(batch), "--osl", str(osl), "--churn-s", "0",
    ]
    t0 = time.time()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"pool_mode": pool_mode, "unroll": unroll,
                "num_pages": num_pages, "error": "timeout"}
    line = None
    for ln in (r.stdout or "").splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            line = json.loads(ln)
    out = {"pool_mode": pool_mode, "unroll": unroll, "num_pages": num_pages,
           "wall_s": round(time.time() - t0, 1)}
    if line is None or r.returncode != 0:
        out["error"] = (r.stderr or "")[-400:] or f"rc={r.returncode}"
        return out
    if "error" in line:
        out["error"] = line["error"]
        return out
    out["decode_tok_s"] = line.get("value")
    out["itl_ms"] = line.get("itl_ms")
    # bench_engine floors the pool at the batch's working-set need; record
    # what actually ran so rows are never mislabeled
    out["num_pages_effective"] = line.get("num_pages", num_pages)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description="decode KV-write strategy sweep")
    ap.add_argument("--quick", action="store_true",
                    help="fewer points (scatter + local@unroll4, 1024/2048 pages)")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--osl", type=int, default=64)
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="per-configuration budget (first runs pay compiles)")
    ap.add_argument("--out", default=None, help="also write results to this file")
    args = ap.parse_args(argv)

    pools = [1024, 2048] if args.quick else [392, 1024, 2048]
    configs = []
    for np_ in pools:
        configs.append(("scatter", 1, np_))
        for u in ([4] if args.quick else [2, 4, 8, 16]):
            configs.append(("local", u, np_))

    results = []
    for mode, unroll, np_ in configs:
        res = run_cfg(mode, unroll, np_, batch=args.batch, osl=args.osl,
                      timeout=args.timeout)
        results.append(res)
        print(json.dumps(res), flush=True)

    # winner per pool size (highest decode tok/s among clean runs)
    summary = {}
    for np_ in pools:
        clean = [r for r in results if r["num_pages"] == np_ and "decode_tok_s" in r]
        if clean:
            best = max(clean, key=lambda r: r["decode_tok_s"])
            summary[str(np_)] = {
                "pool_mode": best["pool_mode"], "unroll": best["unroll"],
                "decode_tok_s": best["decode_tok_s"], "itl_ms": best["itl_ms"],
            }
    print(json.dumps({"sweep_summary": summary}), flush=True)
    if args.out:
        Path(args.out).write_text(json.dumps(
            {"results": results, "summary": summary}, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
