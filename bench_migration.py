"""Migration durability bench: checkpoint-assisted resume vs recompute.

Durable decode sessions (ISSUE 15, docs/fault_tolerance.md): with
incremental commit + session checkpointing, a worker death costs the
survivor an onboard of the replicated session prefix plus a recompute of
only the un-checkpointed tail — instead of a full prefill of
prompt + already-emitted tokens.

Two in-proc engines (A = victim, B = survivor) join one discovery plane,
exactly like the kv-fabric bench arm:

  arm `ckpt`      DYN_KV_CHECKPOINT=<N>: deep sessions decode on A, their
                  committed blocks replicate into B's host tier; A is then
                  killed (data plane + mesh down, streams severed) and the
                  migration-shaped retry (prompt + emitted tokens,
                  migration=1) resumes on B — TTFT is the resume cost.
  arm `recompute` DYN_KV_CHECKPOINT=off: same kill, same retry, but B has
                  nothing — full prefill recompute.

Both arms pre-pay compile + inject variants with an untimed warmup
session, then time `--rounds` resumes each; the gate compares MEDIANS.
Greedy streams are byte-checked against the uninterrupted oracle: the
resumed continuation must be exactly the tokens the dead stream would
have produced (count-contiguity is a corollary).

--smoke gates (CI):  median ckpt TTFT <= --max-ratio x median recompute
TTFT, resume_source_checkpoint > 0 on B, and byte-identical
continuations on every round. The real-hardware claim rides the
`engine_migration` bench_watchdog phase.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time


def _jsonl(obj):
    print(json.dumps(obj), flush=True)


async def _build_mesh(checkpoint: str, *, page_size: int, host_blocks: int,
                      num_pages: int):
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.kvbm import KvbmDistributed
    from dynamo_tpu.llm.kv_transfer import KvDataPlaneServer
    from dynamo_tpu.models import llama
    from dynamo_tpu.runtime import DiscoveryServer, DistributedRuntime, RuntimeConfig

    os.environ["DYN_KV_CHECKPOINT"] = checkpoint
    cfg_model = llama.LlamaConfig.tiny(dtype=jnp.float32)
    params = llama.init_params(cfg_model, jax.random.PRNGKey(0))
    server = DiscoveryServer(port=0)
    _, port = await server.start()
    rcfg = RuntimeConfig(discovery_endpoint=f"127.0.0.1:{port}")
    drts, engines, dists, planes = [], [], [], []
    for _ in range(2):
        drt = await DistributedRuntime.create(rcfg)
        eng = JaxEngine(
            EngineConfig(
                model="tiny", max_num_seqs=4, page_size=page_size,
                num_pages=num_pages, max_model_len=4096,
                prefill_buckets=(32, 64, 128), max_prefill_chunk=128,
                kvbm_host_blocks=host_blocks,
            ),
            model_config=cfg_model, params=params,
        )
        dpl = KvDataPlaneServer()
        await dpl.start()
        await dpl.register(drt)
        dist = KvbmDistributed(drt, eng.kvbm, dpl, "ns", "bench",
                               drt.instance_id)
        await dist.start()
        drts.append(drt)
        engines.append(eng)
        dists.append(dist)
        planes.append(dpl)
    return server, drts, engines, dists, planes


async def _teardown(server, drts, engines, dists, planes):
    for eng in engines:
        await eng.close()
    for d in dists:
        await d.close()
    for p in planes:
        await p.close()
    for drt in drts:
        await drt.close()
    await server.stop()


async def _run_stream(engine, prompt, max_tokens, request_id,
                      migration=0, exclude=None):
    """Drive one greedy stream; returns (tokens, ttft_s)."""
    from dynamo_tpu.llm.protocols import PreprocessedRequest
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        token_ids=list(prompt),
        stop_conditions={"max_tokens": max_tokens, "ignore_eos": True},
        request_id=request_id, migration=migration,
        router={"exclude_instances": exclude} if exclude else {},
    ).to_dict()
    toks, t0, ttft = [], time.perf_counter(), None
    async for item in engine.generate(req, Context()):
        data = item.get("data")
        if data and data.get("token_ids"):
            if ttft is None:
                ttft = time.perf_counter() - t0
            toks.extend(data["token_ids"])
    return toks, ttft if ttft is not None else time.perf_counter() - t0


def _session_prompt(i: int, n: int):
    # distinct per-session prompts: no cross-session prefix reuse blurs
    # the arms (each resume pays its own onboard/recompute)
    return [(7 + i * 131 + j * 3) % 250 + 1 for j in range(n)]


async def _run_arm(name: str, checkpoint: str, args) -> dict:
    server, drts, engines, dists, planes = await _build_mesh(
        checkpoint, page_size=args.page_size,
        host_blocks=args.host_blocks, num_pages=args.num_pages,
    )
    eng_a, eng_b = engines
    dist_b = dists[1]
    plane_b = planes[1]
    n_sessions = args.rounds + 1  # session 0 = untimed warmup
    try:
        # warm B's compile variants with a short plain stream (untimed)
        await _run_stream(eng_b, _session_prompt(99, args.prompt), 8, "warm-b")

        sessions = []
        for i in range(n_sessions):
            prompt = _session_prompt(i, args.prompt)
            toks, _ = await _run_stream(
                eng_a, prompt, args.decode, f"s{i}"
            )
            assert len(toks) == args.decode, (len(toks), args.decode)
            sessions.append((prompt, toks))

        want_blocks = (
            (args.prompt + args.decode) // args.page_size - 1
        ) * n_sessions
        if checkpoint != "off":
            # wait for replication to drain into B's host tier
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                if plane_b.checkpoint_blocks_received >= want_blocks:
                    break
                await asyncio.sleep(0.02)

        # kill A: streams sever, its data plane and mesh go dark — the
        # lease lingers exactly like a real SIGKILL corpse
        await eng_a.close()
        await dists[0].close()
        await planes[0].close()
        await drts[0].server.stop()

        ttfts, mismatches = [], 0
        for i, (prompt, toks) in enumerate(sessions):
            cut = args.cut if args.cut > 0 else args.decode // 2
            emitted = toks[:cut]
            retry_prompt = list(prompt) + emitted
            cont, ttft = await _run_stream(
                eng_b, retry_prompt, args.decode - cut, f"s{i}-retry",
                migration=1, exclude=[drts[0].instance_id],
            )
            if cont != toks[cut:]:
                mismatches += 1
            if i > 0:  # session 0 pre-pays inject/prefill variants
                ttfts.append(ttft)
        st = eng_b.stats()
        return {
            "arm": name,
            "ttft_ms_median": round(statistics.median(ttfts) * 1000.0, 2),
            "ttft_ms_all": [round(t * 1000.0, 2) for t in ttfts],
            "mismatched_streams": mismatches,
            "resume_source_checkpoint": st["resume_source_checkpoint"],
            "resume_source_local": st["resume_source_local"],
            "resume_source_peer": st["resume_source_peer"],
            "resume_source_recompute": st["resume_source_recompute"],
            "migrations_resumed": st["migrations_resumed"],
            "migration_replayed_tokens": st["migration_replayed_tokens"],
            "ckpt_blocks_received_by_b": plane_b.checkpoint_blocks_received,
        }
    finally:
        os.environ.pop("DYN_KV_CHECKPOINT", None)
        try:
            await _teardown(server, drts[1:], engines[1:], dists[1:], planes[1:])
        except Exception:  # noqa: BLE001 — teardown of a half-killed mesh
            pass


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--prompt", type=int, default=64)
    ap.add_argument("--decode", type=int, default=448)
    ap.add_argument("--cut", type=int, default=0,
                    help="tokens emitted before the kill (0 = decode/2)")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--num-pages", type=int, default=256)
    ap.add_argument("--host-blocks", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--checkpoint", default="512",
                    help="DYN_KV_CHECKPOINT for the ckpt arm")
    ap.add_argument("--max-ratio", type=float, default=0.5,
                    help="smoke gate: ckpt TTFT <= ratio x recompute TTFT")
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    ck = asyncio.run(_run_arm("ckpt", args.checkpoint, args))
    _jsonl(ck)
    rc = asyncio.run(_run_arm("recompute", "off", args))
    _jsonl(rc)
    ratio = ck["ttft_ms_median"] / max(rc["ttft_ms_median"], 1e-9)
    summary = {
        "summary": "migration-resume",
        "ckpt_ttft_ms": ck["ttft_ms_median"],
        "recompute_ttft_ms": rc["ttft_ms_median"],
        "ratio": round(ratio, 3),
        "gate_max_ratio": args.max_ratio,
    }
    _jsonl(summary)
    if args.smoke:
        ok = (
            ratio <= args.max_ratio
            and ck["resume_source_checkpoint"] > 0
            and ck["mismatched_streams"] == 0
            and rc["mismatched_streams"] == 0
        )
        if not ok:
            _jsonl({"smoke": "FAIL", **summary,
                    "resume_source_checkpoint": ck["resume_source_checkpoint"],
                    "mismatches": [ck["mismatched_streams"],
                                   rc["mismatched_streams"]]})
            sys.exit(1)
        _jsonl({"smoke": "ok"})


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
