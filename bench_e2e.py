"""End-to-end serving benchmark: the north-star harness.

Drives the FULL product — discovery + OpenAI HTTP frontend + router +
JAX worker(s) as real OS processes — with a ShareGPT-shaped trace at fixed
QPS, and reports output tok/s + p50/p99 TTFT/ITL measured at the client.
This is the genai-perf role for the TPU build (reference: benchmarks/utils/,
docs/benchmarks/benchmarking.md; load-spec shape from
recipes/llama-3-70b/vllm/disagg-single-node/perf.yaml:45-58).

Deployment modes (BASELINE.json configs 1-3):
  * agg     — one aggregated worker (config 1)
  * disagg  — prefill + decode workers, KV pull data plane (config 2)
  * kv      — N aggregated workers behind the KV-aware router (config 3)

Measurement method: prompts are PRE-TOKENIZED int arrays (exact ISL), with
`nvext.ignore_eos` + max_tokens pinning the output length (exact OSL) — so
token accounting is exact without trusting chunk framing. TTFT = first SSE
content chunk; ITL = (t_last - t_first) / (osl - 1) per request (tokens
arrive in K-step engine blocks; the per-request average is the honest
number, per-gap percentiles would read the block cadence instead).

Usage:  python bench.py --e2e [--mode agg|disagg|kv] [--smoke] ...
   or:  python bench_e2e.py --mode disagg --smoke
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import List, Optional

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

from bench import baseline_ratio, ensure_backend  # noqa: E402 — shared baseline
from tests.utils import ManagedProcess, free_port  # noqa: E402


# --------------------------------------------------------------------- #
# trace generation (ShareGPT-shaped, seeded)
# --------------------------------------------------------------------- #


@dataclass
class TraceRequest:
    at: float  # arrival offset from t0 (s)
    isl: int
    osl: int
    token_ids: List[int]


@dataclass
class RequestResult:
    ok: bool
    isl: int = 0
    osl: int = 0
    t_send: float = 0.0
    t_first: float = 0.0
    t_last: float = 0.0
    n_chunks: int = 0
    error: str = ""
    remote_prefill: bool = False


def build_trace(
    n_requests: int,
    qps: float,
    isl_mean: int,
    osl_mean: int,
    max_isl: int,
    max_osl: int,
    vocab: int,
    seed: int = 0,
    prefix_ratio: float = 0.0,
) -> List[TraceRequest]:
    """ShareGPT-shaped lengths: lognormal ISL/OSL (the dataset's heavy right
    tail), Poisson arrivals at fixed mean QPS. Fully seeded => identical
    trace across runs/modes. `prefix_ratio` > 0 gives that fraction of
    requests a shared system-prompt prefix (KV-router prefix-reuse load,
    reference benchmarks/router/prefix_ratio_benchmark.py)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    # lognormal with sigma=0.7 ~ ShareGPT-ish spread; scale so the MEAN of
    # the clipped distribution is ~isl_mean
    sigma = 0.7
    mu_i = np.log(isl_mean) - sigma * sigma / 2
    mu_o = np.log(osl_mean) - sigma * sigma / 2
    isl = np.clip(rng.lognormal(mu_i, sigma, n_requests).astype(int), 4, max_isl)
    osl = np.clip(rng.lognormal(mu_o, sigma, n_requests).astype(int), 4, max_osl)
    gaps = rng.exponential(1.0 / qps, n_requests)
    gaps[0] = 0.0
    arrivals = np.cumsum(gaps)
    # the shared prefix must span at least one full KV page (64 tokens at
    # the worker default) — prefix-cache hits are whole committed blocks,
    # so a sub-page prefix can never be reused and the kv-vs-round-robin
    # comparison would measure load balancing only
    shared_prefix = rng.randint(5, vocab - 1, size=max(isl_mean // 2, 64)).tolist()
    out = []
    for i in range(n_requests):
        n = int(isl[i])
        if prefix_ratio > 0 and rng.rand() < prefix_ratio:
            body = rng.randint(5, vocab - 1, size=max(n - len(shared_prefix), 4))
            toks = (shared_prefix + body.tolist())[:n]
        else:
            toks = rng.randint(5, vocab - 1, size=n).tolist()
        out.append(
            TraceRequest(at=float(arrivals[i]), isl=n, osl=int(osl[i]), token_ids=toks)
        )
    return out


def synthesize_mooncake_trace(
    n_requests: int,
    qps: float,
    block_size: int,
    seed: int = 0,
    n_roots: int = 4,
    depth: int = 3,
    leaf_blocks: int = 2,
    osl_mean: int = 64,
) -> List[dict]:
    """Mooncake-style rows with REAL temporal + prefix structure: a radix
    tree of `n_roots` root chains (depth `depth` shared blocks), requests
    pick a root and extend it with unique leaf blocks, arrivals are
    bursty (sessions re-arrive close together — the locality a synthetic
    prefix-ratio trace lacks). Schema matches the reference's
    benchmarks/prefix_data_generator synthesizer: timestamp(ms),
    input_length, output_length, hash_ids."""
    import numpy as np

    rng = np.random.RandomState(seed)
    # shared core tree: root r's path = [r*1000 + d for d in range(depth)]
    rows = []
    t_ms = 0.0
    next_leaf = 10_000_000
    for i in range(n_requests):
        # bursty arrivals: occasional session bursts at ~4x rate
        gap = rng.exponential(1.0 / qps) * (0.25 if rng.rand() < 0.3 else 1.0)
        t_ms += gap * 1000.0
        root = int(rng.randint(n_roots))
        d = int(rng.randint(1, depth + 1))
        path = [root * 1000 + k for k in range(d)]
        n_leaf = int(rng.randint(1, leaf_blocks + 1))
        path += list(range(next_leaf, next_leaf + n_leaf))
        next_leaf += n_leaf
        isl = len(path) * block_size - int(
            rng.randint(0, max(block_size // 2, 1))
        )
        rows.append({
            "timestamp": int(t_ms),
            "input_length": isl,
            "output_length": max(4, int(rng.poisson(osl_mean))),
            "hash_ids": path,
        })
    return rows


def load_mooncake_trace(
    rows_or_path,
    vocab: int,
    max_isl: int,
    max_osl: int,
    block_size: int,
    speedup: float = 1.0,
    seed: int = 0,
) -> List[TraceRequest]:
    """Mooncake-style JSONL → TraceRequest replay list (reference
    benchmarks/router/real_data_benchmark.py input schema). Every hash_id
    deterministically expands to the same `block_size` token block, so
    rows sharing a hash-id path share a real token prefix the KV router /
    prefix cache can exploit; arrivals follow the trace's timestamps
    (scaled by `speedup`)."""
    import numpy as np

    if isinstance(rows_or_path, (str, Path)):
        with open(rows_or_path) as f:
            rows = [json.loads(ln) for ln in f if ln.strip()]
    else:
        rows = list(rows_or_path)
    if not rows:
        raise ValueError("empty trace")
    rows.sort(key=lambda r: r["timestamp"])
    t0 = rows[0]["timestamp"]

    def block_tokens(hid: int) -> List[int]:
        r = np.random.RandomState((seed * 0x9E3779B1 + int(hid)) & 0x7FFFFFFF)
        return r.randint(5, vocab - 1, size=block_size).tolist()

    out = []
    for i, row in enumerate(rows):
        isl = min(int(row["input_length"]), max_isl)
        osl = max(min(int(row["output_length"]), max_osl), 1)
        toks: List[int] = []
        for hid in row.get("hash_ids") or []:
            if len(toks) >= isl:
                break
            toks.extend(block_tokens(hid))
        if len(toks) > isl:
            toks = toks[:isl]  # tail block truncates; leading blocks intact
        elif len(toks) < isl:
            r = np.random.RandomState((seed ^ (i * 2654435761)) & 0x7FFFFFFF)
            toks.extend(
                r.randint(5, vocab - 1, size=isl - len(toks)).tolist()
            )
        out.append(TraceRequest(
            at=(row["timestamp"] - t0) / 1000.0 / max(speedup, 1e-6),
            isl=len(toks), osl=osl, token_ids=toks,
        ))
    return out


# --------------------------------------------------------------------- #
# deployment: spawn the real stack
# --------------------------------------------------------------------- #


@dataclass
class Deployment:
    procs: List[ManagedProcess] = field(default_factory=list)
    http_port: int = 0
    discovery: str = ""

    def stop(self):
        for p in reversed(self.procs):
            p.stop()


def launch(mode: str, model: str, *, cpu: bool, num_workers: int = 2,
           num_pages: Optional[int] = None, max_num_seqs: int = 64,
           disagg_threshold: int = 64, log_dir: str = "/tmp",
           router_override: Optional[str] = None,
           quantize: Optional[str] = None,
           sched_policy: Optional[str] = None,
           ttft_slo_ms: Optional[float] = None,
           itl_slo_ms: Optional[float] = None) -> Deployment:
    """Spawn discovery + frontend + workers (real processes, real sockets) —
    the same wiring a production deployment uses, per
    jax_worker/__main__.py + frontend/__main__.py."""
    if num_pages is None:
        # one worker: auto-size the pool from free HBM (engine does it).
        # Several workers share ONE chip here (the bench environment has a
        # single tunnel-attached device): concurrent auto-sizing would race
        # for the same free bytes, so give each a fixed conservative slice.
        num_pages = 0 if mode == "agg" else 384
    dep = Deployment()
    disc_port = free_port()
    http_port = free_port()
    disc = f"127.0.0.1:{disc_port}"
    env = {"DYN_DISCOVERY_ENDPOINT": disc}
    # the e2e bench measures latency/throughput of ADMITTED traffic, so the
    # admission gate defaults OFF here: on a loaded host the real-engine
    # TTFT brushes the 2s SLA target and the gate's 429 shed turns an
    # honest latency measurement into failed requests (the PR-13 tier-1
    # agg-smoke flake). Overload behavior has its own harness
    # (bench_serving_overhead --overload-smoke). Export DYN_GATE=1 to
    # re-enable for a gated arm.
    import os as _os

    env.setdefault("DYN_GATE", _os.environ.get("DYN_GATE", "0"))
    # dynosched knobs ride the env so every worker role (and a disagg
    # decode worker's router) sees the same policy/targets
    if sched_policy:
        env["DYN_SCHED_POLICY"] = sched_policy
    if ttft_slo_ms is not None:
        env["DYN_SLA_TTFT_MS"] = str(ttft_slo_ms)
    if itl_slo_ms is not None:
        env["DYN_SLA_ITL_MS"] = str(itl_slo_ms)

    d = ManagedProcess(
        ["-m", "dynamo_tpu.runtime.discovery", "--host", "127.0.0.1",
         "--port", str(disc_port)],
        name="bench-discovery", env=env,
    )
    d.start(f"{log_dir}/bench_e2e_discovery.log")
    d.wait_port(disc_port)
    dep.procs.append(d)

    worker_args = [
        "-m", "dynamo_tpu.jax_worker", "--model", model,
        "--model-name", "bench", "--num-pages", str(num_pages),
        "--max-num-seqs", str(max_num_seqs),
        *(["--quantize", quantize] if quantize else []),
    ]
    router_mode = "round-robin"
    if mode == "agg":
        specs = [("bench-worker", worker_args + ["--role", "aggregated"])]
    elif mode == "disagg":
        specs = [
            ("bench-prefill", worker_args + ["--role", "prefill"]),
            ("bench-decode", worker_args
             + ["--role", "decode", "--disagg-threshold", str(disagg_threshold)]),
        ]
    elif mode == "kv":
        router_mode = "kv"
        specs = [
            (f"bench-worker{i}", worker_args + ["--role", "aggregated", "--kv-events"])
            for i in range(num_workers)
        ]
    else:
        raise ValueError(f"unknown mode {mode!r}")

    for name, args in specs:
        w = ManagedProcess(args, name=name, env=env, cpu_only=cpu)
        w.start(f"{log_dir}/bench_e2e_{name}.log")
        dep.procs.append(w)

    f = ManagedProcess(
        ["-m", "dynamo_tpu.frontend", "--http-port", str(http_port),
         "--router-mode", router_override or router_mode],
        name="bench-frontend", env=env,
    )
    f.start(f"{log_dir}/bench_e2e_frontend.log")
    f.wait_port(http_port)
    dep.procs.append(f)
    dep.http_port = http_port
    dep.discovery = disc
    return dep


def scrape_prefix_hits(disc: str, expect: int = 2, timeout: float = 10.0) -> int:
    """Total prefix-cache hit blocks across the worker pool, read from the
    workers' published stats (the router-benefit oracle)."""
    from tests.utils import scrape_worker_stats

    per_worker = scrape_worker_stats(disc, min_workers=expect, timeout=timeout)
    return sum(
        int(s.get("kv_prefix_hit_blocks_total", 0)) for s in per_worker.values()
    )


async def wait_model(port: int, timeout: float) -> None:
    import aiohttp

    deadline = time.time() + timeout
    async with aiohttp.ClientSession() as s:
        while time.time() < deadline:
            try:
                async with s.get(f"http://127.0.0.1:{port}/v1/models") as r:
                    if r.status == 200:
                        data = await r.json()
                        if any(m["id"] == "bench" for m in data.get("data", [])):
                            return
            except aiohttp.ClientError:
                pass
            await asyncio.sleep(0.5)
    raise TimeoutError(f"model not registered within {timeout}s")


# --------------------------------------------------------------------- #
# load driver
# --------------------------------------------------------------------- #


async def drive_one(session, port: int, tr: TraceRequest) -> RequestResult:
    body = {
        "model": "bench",
        "prompt": tr.token_ids,
        "max_tokens": tr.osl,
        "stream": True,
        # sampled, not greedy: a random-weight bench model under argmax can
        # lock onto special tokens (PAD/BOS/EOS), which correctly detokenize
        # to no text — and a zero-text stream has no TTFT signal
        "temperature": 1.0,
        "nvext": {"ignore_eos": True, "annotations": ["remote_prefill"]},
    }
    res = RequestResult(ok=False, isl=tr.isl, osl=tr.osl, t_send=time.perf_counter())
    try:
        async with session.post(
            f"http://127.0.0.1:{port}/v1/completions", json=body
        ) as resp:
            if resp.status != 200:
                res.error = f"http {resp.status}: {(await resp.text())[:200]}"
                return res
            # parse the SSE stream: every `data:` JSON with non-empty text is
            # token content; `: event [...]` comment lines carry annotations
            # (worker_instance_id, remote_prefill)
            async for raw in resp.content:
                line = raw.decode("utf-8", "replace").strip()
                if not line:
                    continue
                if line.startswith(": "):
                    if "remote_prefill" in line:
                        res.remote_prefill = True
                    continue
                if not line.startswith("data:"):
                    continue
                payload = line[5:].strip()
                if payload == "[DONE]":
                    break
                try:
                    chunk = json.loads(payload)
                except json.JSONDecodeError:
                    continue
                if chunk.get("error"):
                    res.error = str(chunk["error"])[:200]
                    return res
                choices = chunk.get("choices") or []
                if choices and choices[0].get("text"):
                    now = time.perf_counter()
                    if res.t_first == 0.0:
                        res.t_first = now
                    res.t_last = now
                    res.n_chunks += 1
        if res.t_first == 0.0:
            res.error = "no content chunks"
            return res
        res.ok = True
        return res
    except Exception as e:  # noqa: BLE001 — a failed request is a data point
        res.error = f"{type(e).__name__}: {e}"
        return res


async def run_trace(port: int, trace: List[TraceRequest]) -> List[RequestResult]:
    import aiohttp

    connector = aiohttp.TCPConnector(limit=0)
    timeout = aiohttp.ClientTimeout(total=None, sock_connect=30)
    async with aiohttp.ClientSession(connector=connector, timeout=timeout) as session:
        t0 = time.perf_counter()
        tasks = []
        for tr in trace:
            delay = tr.at - (time.perf_counter() - t0)
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(asyncio.create_task(drive_one(session, port, tr)))
        return list(await asyncio.gather(*tasks))


def percentile(xs: List[float], p: float) -> float:
    if not xs:
        return 0.0  # all-failed run: keep the result line strict-JSON (no NaN)
    xs = sorted(xs)
    k = min(int(round((p / 100) * (len(xs) - 1))), len(xs) - 1)
    return xs[k]


def sla_fields(results: List[RequestResult], ttft_slo_ms: float,
               itl_slo_ms: float, wall: float) -> dict:
    """SLA-attainment block: the fraction of successful requests meeting
    each target, plus goodput (output tok/s counting ONLY requests that
    met every set target — the number an SLA-priced deployment actually
    sells). Failed requests count as misses by construction."""
    ok = [r for r in results if r.ok]
    n_all = max(len(results), 1)
    ttft_met = [r for r in ok if (r.t_first - r.t_send) * 1000 <= ttft_slo_ms]
    out = {
        "ttft_target_ms": ttft_slo_ms,
        "ttft_attainment": round(len(ttft_met) / n_all, 3),
    }
    good = ttft_met
    if itl_slo_ms:
        itl_met = [
            r for r in ok
            if r.osl <= 1
            or (r.t_last - r.t_first) / (r.osl - 1) * 1000 <= itl_slo_ms
        ]
        out["itl_target_ms"] = itl_slo_ms
        out["itl_attainment"] = round(len(itl_met) / n_all, 3)
        met_ids = set(id(r) for r in itl_met)
        good = [r for r in ttft_met if id(r) in met_ids]
    out["goodput_tok_s"] = round(sum(r.osl for r in good) / wall, 1)
    return out


def summarize(results: List[RequestResult], wall: float, mode: str, qps: float,
              model: str) -> dict:
    ok = [r for r in results if r.ok]
    failed = [r for r in results if not r.ok]
    out_tokens = sum(r.osl for r in ok)
    ttft = [(r.t_first - r.t_send) * 1000 for r in ok]
    itl = [
        (r.t_last - r.t_first) / (r.osl - 1) * 1000 for r in ok if r.osl > 1
    ]
    e2e_lat = [(r.t_last - r.t_send) * 1000 for r in ok]
    summary = {
        "mode": mode,
        "model": model,
        "qps": qps,
        "requests": len(results),
        "failed": len(failed),
        "wall_s": round(wall, 2),
        "output_tok_s": round(out_tokens / wall, 1),
        "total_tok_s": round(
            (out_tokens + sum(r.isl for r in ok)) / wall, 1
        ),
        "ttft_ms": {
            "p50": round(percentile(ttft, 50), 1),
            "p99": round(percentile(ttft, 99), 1),
        },
        "itl_ms": {
            "p50": round(percentile(itl, 50), 2),
            "p99": round(percentile(itl, 99), 2),
        },
        "latency_ms": {
            "p50": round(percentile(e2e_lat, 50), 1),
            "p99": round(percentile(e2e_lat, 99), 1),
        },
        "remote_prefills": sum(1 for r in ok if r.remote_prefill),
    }
    if failed:
        summary["first_error"] = failed[0].error
    return summary


# --------------------------------------------------------------------- #
# main
# --------------------------------------------------------------------- #


def main(argv: Optional[List[str]] = None):
    ap = argparse.ArgumentParser(description="dynamo-tpu e2e serving benchmark")
    ap.add_argument("--smoke", action="store_true", help="CPU, tiny model, short trace")
    ap.add_argument("--mode", choices=["agg", "disagg", "kv"], default="agg")
    ap.add_argument("--model", default=None)
    ap.add_argument("--qps", type=float, default=None)
    ap.add_argument("--requests", type=int, default=None)
    ap.add_argument("--isl-mean", type=int, default=220, help="ShareGPT-ish mean input len")
    ap.add_argument("--osl-mean", type=int, default=180, help="ShareGPT-ish mean output len")
    ap.add_argument("--max-isl", type=int, default=2048)
    ap.add_argument("--max-osl", type=int, default=512)
    ap.add_argument("--num-workers", type=int, default=2, help="workers in kv mode")
    ap.add_argument("--num-pages", type=int, default=None,
                    help="KV pool per worker (default: auto for agg, a fixed "
                    "conservative slice for multi-worker single-chip modes)")
    ap.add_argument("--prefix-ratio", type=float, default=0.0)
    ap.add_argument("--trace", default=None, metavar="FILE|synth",
                    help="replay a mooncake-style trace (JSONL rows with "
                    "timestamp/input_length/output_length/hash_ids — "
                    "reference benchmarks/router/real_data_benchmark.py) "
                    "instead of the synthetic lognormal trace; 'synth' "
                    "generates a bursty radix-tree trace in-process")
    ap.add_argument("--trace-block-size", type=int, default=None,
                    help="tokens per hash_id block (default: 512, or the "
                    "KV page size in --smoke mode)")
    ap.add_argument("--trace-speedup", type=float, default=1.0,
                    help="replay the trace N× faster than recorded")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--startup-timeout", type=float, default=None)
    # dynosched (engine/scheduler/): worker scheduling policy + the SLA
    # targets both the workers optimize for and the report grades against
    ap.add_argument("--sched-policy", choices=["fifo", "sla"], default=None,
                    help="worker step-scheduling policy (DYN_SCHED_POLICY); "
                    "default: workers' own env/default (fifo)")
    ap.add_argument("--ttft-slo-ms", type=float, default=2000.0,
                    help="TTFT target: fed to workers as DYN_SLA_TTFT_MS "
                    "and used for the attainment report")
    ap.add_argument("--itl-slo-ms", type=float, default=100.0,
                    help="ITL target: fed to workers as DYN_SLA_ITL_MS and "
                    "used for the attainment report (0 = off)")
    ap.add_argument("--sla-compare", action="store_true",
                    help="run the identical trace twice — workers under "
                    "DYN_SCHED_POLICY=fifo then =sla — and report TTFT/"
                    "tok-s/attainment side by side (the scheduler-benefit "
                    "oracle, reference: --router-compare)")
    ap.add_argument("--quantize", choices=["int8"], default=None,
                    help="worker weight quantization (models/quant.py)")
    ap.add_argument("--router-compare", action="store_true",
                    help="kv mode: ALSO run the identical trace through a "
                    "round-robin frontend over a fresh identical worker "
                    "pool and report the router's benefit (TTFT delta + "
                    "prefix-cache hit blocks) — reference "
                    "benchmarks/router/prefix_ratio_benchmark.py role")
    args = ap.parse_args(argv)

    cpu = bool(args.smoke)
    model = args.model or ("tiny" if args.smoke else "llama3-3b")
    if not cpu:
        unavailable = ensure_backend(f"e2e_output_toks_{args.mode}_{model}")
        if unavailable is not None:
            print(json.dumps(unavailable))
            return 0
    qps = args.qps or (8.0 if args.smoke else 4.0)
    n_requests = args.requests or (32 if args.smoke else 96)
    # TPU first runs pay uncached engine compiles through the tunnel
    # (~20-40s each across several program variants)
    startup = args.startup_timeout or (120.0 if args.smoke else 600.0)
    if args.smoke:
        args.isl_mean = min(args.isl_mean, 96)
        args.osl_mean = min(args.osl_mean, 32)
        args.max_isl, args.max_osl = 256, 64
    vocab = 512 if model in ("tiny", "tiny-moe") else 128000

    if args.trace:
        block = args.trace_block_size or (64 if args.smoke else 512)
        rows = (
            synthesize_mooncake_trace(
                n_requests, qps, block, seed=args.seed,
                osl_mean=args.osl_mean,
            )
            if args.trace == "synth" else args.trace
        )
        trace = load_mooncake_trace(
            rows, vocab, args.max_isl, args.max_osl, block,
            speedup=args.trace_speedup, seed=args.seed,
        )
        n_requests = len(trace)
    else:
        trace = build_trace(
            n_requests, qps, args.isl_mean, args.osl_mean, args.max_isl,
            args.max_osl, vocab, seed=args.seed, prefix_ratio=args.prefix_ratio,
        )
    print(
        f"# e2e bench: mode={args.mode} model={model} device="
        f"{'cpu' if cpu else 'tpu'} qps={qps} requests={n_requests} "
        f"isl~{args.isl_mean} osl~{args.osl_mean}",
        file=sys.stderr,
    )

    def run_arm(router_override=None, sched_policy=None):
        """One deployment + trace run; returns (summary, prefix_hit_blocks)."""
        dep = launch(args.mode, model, cpu=cpu, num_workers=args.num_workers,
                     num_pages=args.num_pages,
                     router_override=router_override, quantize=args.quantize,
                     sched_policy=sched_policy or args.sched_policy,
                     ttft_slo_ms=args.ttft_slo_ms, itl_slo_ms=args.itl_slo_ms)
        hits = 0
        dispatch = {}
        n_reporting = 0
        # (component topic, workers expected) per mode: kv runs a backend
        # pool, disagg runs decode (backend) + prefill on SEPARATE metric
        # topics, agg runs one backend worker
        scrape_plan = (
            [("backend", 1), ("prefill", 1)] if args.mode == "disagg"
            else [("backend", args.num_workers if args.mode == "kv" else 1)]
        )

        def _scrape_dispatch():
            from tests.utils import scrape_worker_stats

            agg = {}
            n = 0
            for component, expect in scrape_plan:
                per_worker = scrape_worker_stats(
                    dep.discovery, min_workers=expect, timeout=15,
                    component=component,
                )
                n += len(per_worker)
                for st in per_worker.values():
                    for k, v in st.items():
                        if k.startswith("dispatch_"):
                            agg[k] = agg.get(k, 0) + v
            return agg, n

        try:
            asyncio.run(wait_model(dep.http_port, startup))
            # brief warmup: compile every engine variant before the timed trace
            warm = [TraceRequest(0.0, 32, 8, list(range(5, 37))) for _ in range(2)]
            asyncio.run(run_trace(dep.http_port, warm))
            # baseline AFTER warmup: engine _dev_time counters are
            # cumulative, so the diagnostic must diff out warmup + compile
            try:
                base_dispatch, _ = _scrape_dispatch()
            except Exception as e:  # noqa: BLE001 — diagnostic only
                print(f"# dispatch-stat baseline scrape failed: {e}",
                      file=sys.stderr)
                base_dispatch = None
            t0 = time.perf_counter()
            results = asyncio.run(run_trace(dep.http_port, trace))
            wall = time.perf_counter() - t0
            if args.router_compare and args.mode == "kv":
                hits = scrape_prefix_hits(dep.discovery, expect=args.num_workers)
            # per-dispatch device occupancy (engine stats()): the
            # serving-gap diagnostic — what fraction of wall the device
            # stream spent in block/prefill/reset/patch, vs idle
            try:
                if base_dispatch is not None:
                    end_dispatch, n_reporting = _scrape_dispatch()
                    dispatch = {
                        k: round(v - base_dispatch.get(k, 0), 3)
                        for k, v in end_dispatch.items()
                    }
            except Exception as e:  # noqa: BLE001 — diagnostic only
                print(f"# dispatch-stat scrape failed: {e}", file=sys.stderr)
        finally:
            dep.stop()
        summary = summarize(results, wall, args.mode, qps, model)
        summary["sla"] = sla_fields(
            results, args.ttft_slo_ms, args.itl_slo_ms, wall
        )
        if dispatch:
            # fetch runs on its own thread and overlaps compute — not part
            # of device-stream occupancy. Seconds are summed across
            # workers, so occupancy averages over the reporting workers.
            busy = sum(
                v for k, v in dispatch.items()
                if k.endswith("_s") and k != "dispatch_fetch_s"
            )
            dispatch["device_busy_frac"] = round(
                busy / max(wall * max(n_reporting, 1), 1e-9), 3
            )
            summary["dispatch"] = dispatch
        return summary, hits

    if args.router_compare and args.mode != "kv":
        ap.error("--router-compare requires --mode kv")
    if args.sla_compare and args.router_compare:
        ap.error("--sla-compare and --router-compare are mutually exclusive")

    if args.sla_compare:
        # identical trace, fresh identical deployments: fifo arm then sla
        # arm — the scheduler-benefit oracle (acceptance: TTFT improves,
        # decode tok/s stays within 5%)
        fifo_summary, _ = run_arm(sched_policy="fifo")
        sla_summary, _ = run_arm(sched_policy="sla")

        def _arm(s):
            return {
                "output_tok_s": s["output_tok_s"],
                "ttft_p50_ms": s["ttft_ms"]["p50"],
                "ttft_p99_ms": s["ttft_ms"]["p99"],
                "itl_p50_ms": s["itl_ms"]["p50"],
                "itl_p99_ms": s["itl_ms"]["p99"],
                "sla": s["sla"],
                "failed": s["failed"],
            }

        benefit = {
            "metric": f"e2e_sla_compare_{args.mode}_{model}_qps{qps:g}",
            "value": round(
                fifo_summary["ttft_ms"]["p50"] - sla_summary["ttft_ms"]["p50"],
                1,
            ),
            "unit": "ms_ttft_p50_saved",
            "vs_baseline": None,
            "ttft_slo_ms": args.ttft_slo_ms,
            "itl_slo_ms": args.itl_slo_ms,
            "fifo": _arm(fifo_summary),
            "sla": _arm(sla_summary),
        }
        print(json.dumps(benefit))
        return 0 if not (fifo_summary["failed"] or sla_summary["failed"]) else 1

    summary, kv_hits = run_arm()

    if args.router_compare and args.mode == "kv":
        # arm B: identical trace, identical fresh pool, round-robin routing
        rr_summary, rr_hits = run_arm(router_override="round-robin")
        trace_tag = (
            f"trace_{Path(args.trace).stem if args.trace != 'synth' else 'synth'}"
            if args.trace else f"prefix{args.prefix_ratio:g}"
        )
        benefit = {
            "metric": f"kv_router_benefit_{model}_{trace_tag}",
            "value": round(rr_summary["ttft_ms"]["p50"] - summary["ttft_ms"]["p50"], 1),
            "unit": "ms_ttft_p50_saved",
            "vs_baseline": None,
            "kv": {"ttft_p50_ms": summary["ttft_ms"]["p50"],
                   "output_tok_s": summary["output_tok_s"],
                   "prefix_hit_blocks": kv_hits,
                   "failed": summary["failed"]},
            "round_robin": {"ttft_p50_ms": rr_summary["ttft_ms"]["p50"],
                            "output_tok_s": rr_summary["output_tok_s"],
                            "prefix_hit_blocks": rr_hits,
                            "failed": rr_summary["failed"]},
        }
        print(json.dumps(benefit))
        return 0 if not (summary["failed"] or rr_summary["failed"]) else 1
    print("# " + json.dumps(summary), file=sys.stderr)
    from bench_eff import efficiency_fields

    # e2e batch varies with load; qps*latency ~ concurrency is the honest
    # denominator for a roofline read. Use the request count in flight at
    # steady state ~ qps * mean_latency (bounded by max_num_seqs).
    mean_lat_s = summary["latency_ms"]["p50"] / 1000.0
    eff_batch = max(1, min(int(qps * mean_lat_s), 64))
    result = {
        "metric": (
            f"e2e_output_toks_{args.mode}_{model}_trace"
            if args.trace else
            f"e2e_output_toks_{args.mode}_{model}_qps{qps:g}"
        ),
        "value": summary["output_tok_s"],
        "unit": "tok/s",
        "vs_baseline": baseline_ratio(summary["output_tok_s"], model),
        "ttft_p50_ms": summary["ttft_ms"]["p50"],
        "ttft_p99_ms": summary["ttft_ms"]["p99"],
        "itl_p50_ms": summary["itl_ms"]["p50"],
        "itl_p99_ms": summary["itl_ms"]["p99"],
        "failed": summary["failed"],
        "sla": summary["sla"],
        **({"sched_policy": args.sched_policy} if args.sched_policy else {}),
        **(efficiency_fields(
            model, summary["output_tok_s"], eff_batch,
            args.isl_mean + args.osl_mean / 2, args.quantize,
        ) if not cpu else {}),
        **({"dispatch": summary["dispatch"]} if "dispatch" in summary else {}),
    }
    print(json.dumps(result))
    if summary["failed"]:
        print(f"# {summary['failed']} requests failed: {summary.get('first_error')}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
