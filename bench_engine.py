"""Engine benchmark: drive JaxEngine.generate THROUGH the product hot path
(admission -> batched prefill -> fused decode blocks -> fetch pipeline ->
emission), not a re-implemented inline loop.

The raw-step bench (bench.py --raw) is the device ceiling; this one includes
the scheduler, the asyncio step loop, carry management, and emission — the
numbers a worker actually delivers. Two phases:

  * steady: admit a full batch at once, measure decode tok/s once every
    lane is decoding (prefill excluded), ITL from block cadence.
  * churn: closed-loop at full concurrency — every finished request is
    replaced immediately, so admissions/finishes continuously disturb the
    decode carry. The gap between steady and churn is exactly the cost of
    carry resets / pipeline drains on admission (round-2 verdict weak #3).

Usage: python bench.py --engine [--smoke] [--batch 32] [--osl 128] ...
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from pathlib import Path
from typing import List, Optional

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

from bench import baseline_ratio, ensure_backend  # noqa: E402


def _make_engine(model: str, B: int, isl: int, osl: int, K: int, page: int = 64,
                 pool_mode=None, unroll: int = 0, quantize=None,
                 num_pages: Optional[int] = None, spec=None,
                 mixed: Optional[bool] = None):
    from dynamo_tpu.engine import EngineConfig, JaxEngine

    max_len = isl + osl + K + page
    if spec:
        max_len += 32  # spec blocks can overshoot by rounds*(1+d) - 1
    pages_per_seq = (max_len + page - 1) // page
    auto_pages = 2 * B * pages_per_seq + 8  # churn headroom: old pages
    # linger in the prefix cache while replacements admit
    cfg = EngineConfig(
        model=model,
        page_size=page,
        num_pages=max(num_pages, auto_pages) if num_pages else auto_pages,
        max_num_seqs=B,
        max_model_len=max_len,
        decode_block_steps=K,
        decode_pool_mode=pool_mode,
        decode_block_unroll=unroll,
        quantize=quantize,
        spec_mode=spec,
        enable_prefix_caching=True,
        mixed_dispatch=mixed,
    )
    return JaxEngine(cfg)


async def _run_one(engine, prompt: List[int], osl: int, times: List[tuple],
                   temperature: float = 1.0, lora_name=None, guided=None):
    """One request through the public engine API; appends (t, n_tokens)
    per emission burst."""
    from dynamo_tpu.llm.protocols import PreprocessedRequest
    from dynamo_tpu.runtime.engine import Context

    req = PreprocessedRequest(
        token_ids=prompt,
        stop_conditions={"max_tokens": osl,
                         **({} if guided else {"ignore_eos": True})},
        sampling_options={"temperature": temperature},
        eos_token_ids=[2] if guided else [],
        lora_name=lora_name,
        guided=guided,
    ).to_dict()
    first = None
    n = 0
    async for item in engine.generate(req, Context()):
        data = item.get("data") if isinstance(item, dict) else None
        if isinstance(item, dict) and item.get("event") == "error":
            print(f"# engine error: {item.get('comment')}", file=sys.stderr)
        if data and data.get("token_ids"):
            now = time.perf_counter()
            if first is None:
                first = now
            n += len(data["token_ids"])
            times.append((now, len(data["token_ids"])))
    return first, n


def _mk_prompt(rng, vocab: int, isl: int, repetitive: bool) -> List[int]:
    """Random tokens, or (for the spec-decode bench) a tiled base pattern —
    the repetition-heavy trace the prompt-lookup drafter exploits."""
    if repetitive:
        base = rng.randint(5, vocab - 1, size=max(isl // 8, 4)).tolist()
        return (base * (isl // len(base) + 1))[:isl]
    return rng.randint(5, vocab - 1, size=isl).tolist()


async def _steady(engine, B: int, isl: int, osl: int, vocab: int, seed: int = 0,
                  repetitive: bool = False):
    import numpy as np

    rng = np.random.RandomState(seed)
    times: List[tuple] = []
    # spec runs greedy: argmax cycles + repeated prompts are the
    # acceptance-friendly regime; plain runs sample (see drive_one note)
    temp = 0.0 if repetitive else 1.0
    tasks = [
        asyncio.create_task(
            _run_one(engine, _mk_prompt(rng, vocab, isl, repetitive), osl,
                     times, temperature=temp)
        )
        for _ in range(B)
    ]
    t0 = time.perf_counter()
    results = await asyncio.gather(*tasks)
    t_end = time.perf_counter()
    firsts = [f for f, _ in results if f is not None]
    total = sum(n for _, n in results)
    if os.environ.get("DYN_BENCH_DUMP_TIMES"):
        # burst-level trace for post-hoc analysis (e.g. "every request's
        # tokens arrived in one burst" — the TPU local-mode signature)
        t_base = min(t for t, _ in times) if times else 0.0
        print("# bursts: " + json.dumps(
            [[round(t - t_base, 4), k] for t, k in sorted(times)]),
            file=sys.stderr)
    if not firsts:
        # every request failed (engine errors surface as error annotations,
        # not emissions) — raise something actionable instead of max([])
        raise RuntimeError(
            f"no request produced tokens ({len(results)} submitted); "
            "engine errors are on stderr above"
        )
    # decode-phase throughput: tokens emitted after every lane has started
    t_all_started = max(firsts)
    decode_toks = sum(k for t, k in times if t > t_all_started)
    decode_span = t_end - t_all_started
    return {
        "total_tokens": total,
        "wall_s": t_end - t0,
        "decode_tok_s": decode_toks / decode_span if decode_span > 0 else 0.0,
        "itl_ms": decode_span / (decode_toks / B) * 1000 if decode_toks else 0.0,
        "ttft_first_ms": (min(firsts) - t0) * 1000,
        "ttft_last_ms": (t_all_started - t0) * 1000,
    }


async def _churn(engine, B: int, isl: int, osl: int, vocab: int,
                 duration_s: float, seed: int = 1):
    """Closed loop: hold concurrency at B; completed requests are replaced
    with fresh prompts until the clock runs out."""
    import numpy as np

    rng = np.random.RandomState(seed)
    times: List[tuple] = []
    stop_at = time.perf_counter() + duration_s
    inflight: set = set()
    completed = 0

    def submit():
        prompt = rng.randint(5, vocab - 1, size=isl).tolist()
        t = asyncio.create_task(_run_one(engine, prompt, osl, times))
        inflight.add(t)

    for _ in range(B):
        submit()
    t0 = time.perf_counter()
    while time.perf_counter() < stop_at:
        done, _ = await asyncio.wait(
            inflight, return_when=asyncio.FIRST_COMPLETED,
            timeout=max(stop_at - time.perf_counter(), 0.01),
        )
        for t in done:
            inflight.discard(t)
            completed += 1
            if time.perf_counter() < stop_at:
                submit()
    if inflight:
        await asyncio.gather(*inflight)
    t_end = time.perf_counter()
    # drop the warmup ramp (first 20% of the window)
    t_lo = t0 + 0.2 * (t_end - t0)
    toks = sum(k for t, k in times if t > t_lo)
    span = t_end - t_lo
    return {
        "completed": completed,
        "wall_s": t_end - t0,
        "churn_tok_s": toks / span if span > 0 else 0.0,
    }


def _pct(xs: List[float], p: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(int(round((len(xs) - 1) * p)), len(xs) - 1)
    return xs[i]


async def _mixed_replay(engine, B: int, isl: int, osl: int, vocab: int,
                        n_arrivals: int, seed: int = 0):
    """Replay a mixed prefill+decode schedule: B decode lanes run long
    generations while `n_arrivals` staggered prompts prefill into the same
    engine — every arrival step is a mixed-opportunity step (prefill work
    + active decode). Per-step wall times are recorded by wrapping the
    engine's own `_step_once` and classified by which path served the step
    (mixed / split-pair / other)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    times: List[tuple] = []
    step_times = {"mixed": [], "split": [], "other": []}

    orig_step = engine._step_once

    async def timed_step():
        m0, s0 = engine.mixed_steps, engine.split_steps
        t0 = time.perf_counter()
        r = await orig_step()
        dt = time.perf_counter() - t0
        kind = (
            "mixed" if engine.mixed_steps > m0
            else "split" if engine.split_steps > s0
            else "other"
        )
        step_times[kind].append(dt * 1000.0)
        return r

    engine._step_once = timed_step
    try:
        # the decode group must outlast the whole arrival schedule, so
        # every arrival's prefill chunks land beside active decode lanes
        osl_dec = max(osl, 16 * n_arrivals)
        decode_tasks = [
            asyncio.create_task(_run_one(
                engine, _mk_prompt(rng, vocab, isl, False), osl_dec, times
            ))
            for _ in range(max(B // 2, 1))
        ]
        await asyncio.sleep(0.25)  # let the decode group reach steady decode
        arrival_tasks = []
        for _ in range(n_arrivals):
            arrival_tasks.append(asyncio.create_task(_run_one(
                engine, _mk_prompt(rng, vocab, isl, False), 4, times,
            )))
            await asyncio.sleep(0.1)  # stagger: chunks land mid-decode
        await asyncio.gather(*decode_tasks, *arrival_tasks)
    finally:
        engine._step_once = orig_step
    return step_times


def _mixed_arm_report(engine, step_times) -> dict:
    s = engine.stats()
    fused = s["mixed_steps"] > 0
    times = step_times["mixed"] if fused else step_times["split"]
    return {
        "mixed_steps": s["mixed_steps"],
        "split_steps": s["split_steps"],
        # device dispatches needed to serve one mixed-opportunity step:
        # the fused path does prefill+decode in ONE call, the split path
        # pays a prefill dispatch AND a decode dispatch
        "dispatches_per_mixed_step": 1 if fused else 2,
        "padding_frac": s["mixed_padding_frac"] if fused
        else s["split_padding_frac"],
        "step_ms_p50": round(_pct(times, 0.50), 2),
        "step_ms_p99": round(_pct(times, 0.99), 2),
        "dispatch_counts": {
            k.removeprefix("dispatch_").removesuffix("_count"): v
            for k, v in s.items()
            if k.startswith("dispatch_") and k.endswith("_count")
        },
    }


def run_mixed_bench(args, model: str, vocab: int, B: int, isl: int, osl: int):
    """`--mixed`: the unified-vs-split comparison on the same seeded
    schedule — dispatches per mixed step (2 -> 1), padding-waste ratio,
    and step-time p50/p99 for each arm (ISSUE 8 acceptance surface)."""
    arms = {}
    for name, flag in (("unified", True), ("split", False)):
        engine = _make_engine(
            model, B, isl, osl, args.block, quantize=args.quantize,
            mixed=flag,
        )

        async def run(eng=engine):
            # warmup: compile the dispatch variants both arms use — the
            # steady pass covers prefill/decode, the short staggered
            # replay covers the mixed variant (its first occurrence pays
            # the XLA compile, which must not pollute step-time p50/p99)
            await _steady(eng, min(B, 2), isl, 8, vocab, seed=99)
            await _mixed_replay(eng, B, isl, osl, vocab,
                                n_arrivals=max(B, 4), seed=99)
            st = await _mixed_replay(eng, B, isl, osl, vocab,
                                     n_arrivals=max(B, 4))
            await eng.close()
            return st

        step_times = asyncio.run(run())
        arms[name] = _mixed_arm_report(engine, step_times)
        print(f"# {name}: {json.dumps(arms[name])}", file=sys.stderr)
    result = {
        "metric": f"engine_mixed_{model}_bs{B}_isl{isl}",
        "value": arms["unified"]["dispatches_per_mixed_step"],
        "unit": "dispatches/mixed-step",
        "split_dispatches_per_mixed_step":
            arms["split"]["dispatches_per_mixed_step"],
        "mixed_padding_frac": arms["unified"]["padding_frac"],
        "split_padding_frac": arms["split"]["padding_frac"],
        "mixed_step_ms_p50": arms["unified"]["step_ms_p50"],
        "mixed_step_ms_p99": arms["unified"]["step_ms_p99"],
        "split_step_ms_p50": arms["split"]["step_ms_p50"],
        "split_step_ms_p99": arms["split"]["step_ms_p99"],
        "mixed_steps": arms["unified"]["mixed_steps"],
        "split_steps": arms["split"]["split_steps"],
    }
    print(json.dumps(result))
    return 0


def _register_bench_adapter(engine):
    """One rank-8 adapter initialized from the engine's own model config —
    the lora traffic class for the blend replay."""
    import jax

    from dynamo_tpu.models import lora as lora_mod

    engine.register_adapters([
        lora_mod.init_adapter(
            engine.model_config, "bench-ad", jax.random.PRNGKey(7), rank=8
        )
    ])


async def _blended_replay(engine, kinds, B: int, isl: int, vocab: int,
                          n_arrivals: int, seed: int = 0):
    """Drive a blended trace: a plain decode group (repetitive prompts
    when the engine runs spec — every decode lane is then a spec lane)
    with staggered guided / lora / plain arrivals prefillng beside it.
    Returns (emitted_tokens, per-step wall times by serving path)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    times: List[tuple] = []
    step_times = {"mixed": [], "split": [], "other": []}
    spec = bool(engine.config.spec_mode)

    orig_step = engine._step_once

    async def timed_step():
        m0, s0 = engine.mixed_steps, engine.split_steps
        t0 = time.perf_counter()
        r = await orig_step()
        dt = time.perf_counter() - t0
        kind = (
            "mixed" if engine.mixed_steps > m0
            else "split" if engine.split_steps > s0
            else "other"
        )
        step_times[kind].append(dt * 1000.0)
        return r

    engine._step_once = timed_step
    total = 0
    try:
        # the decode group must outlast the arrival schedule (spec blocks
        # advance up to rounds*(1+d) tokens, so spec needs a longer osl)
        osl_dec = max(32, (96 if spec else 12) * n_arrivals)
        decode_tasks = [
            asyncio.create_task(_run_one(
                engine, _mk_prompt(rng, vocab, isl, spec), osl_dec, times,
                temperature=0.0,
            ))
            for _ in range(max(B // 2, 1))
        ]
        await asyncio.sleep(0.25)
        arrival_kinds = [k for k in kinds if k != "spec"] or ["plain"]
        arrival_tasks = []
        for i in range(n_arrivals):
            kind = arrival_kinds[i % len(arrival_kinds)]
            kw = {}
            if kind == "guided":
                kw["guided"] = {"kind": "choice", "choices": ["yes", "no"]}
            elif kind == "lora":
                kw["lora_name"] = "bench-ad"
            arrival_tasks.append(asyncio.create_task(_run_one(
                engine, _mk_prompt(rng, vocab, isl, False), 6, times,
                temperature=0.0, **kw,
            )))
            await asyncio.sleep(0.1)
        results = await asyncio.gather(*decode_tasks, *arrival_tasks)
        total = sum(n for _, n in results)
    finally:
        engine._step_once = orig_step
    return total, step_times


def run_blend_bench(args, model: str, vocab: int, B: int, isl: int, osl: int):
    """`--mixed --blend guided:lora:spec`: blended-workload fusion. The
    unified arm serves every kind on the ONE ragged dispatch (spec verify
    rows included); the split arm is the servable pre-fusion reference —
    per-kind dedicated programs, and NON-spec when the blend includes
    spec (guided/lora were inadmissible under the split spec lane).
    Headline: emitted tokens per device dispatch, plus per-kind fused
    row counts and mixed_coverage_frac for the unified arm."""
    kinds = [k for k in args.blend.split(":") if k]
    # size max_model_len for the replay's long decode group, not the
    # nominal --osl (the group must outlast the whole arrival schedule)
    osl_eng = max(osl, (96 if "spec" in kinds else 12) * max(B, 4))
    arms = {}
    for name, flag in (("unified", True), ("split", False)):
        spec = "ngram" if ("spec" in kinds and flag) else None
        engine = _make_engine(
            model, B, isl, osl_eng, args.block, quantize=args.quantize,
            spec=spec, mixed=flag,
        )
        if "lora" in kinds:
            _register_bench_adapter(engine)

        async def run(eng=engine):
            await _steady(eng, min(B, 2), isl, 8, vocab, seed=99,
                          repetitive=bool(spec))
            await _blended_replay(eng, kinds, B, isl, vocab,
                                  n_arrivals=max(B, 4), seed=99)
            d0 = {k: v for k, v in eng.stats().items()
                  if k.startswith("dispatch_") and k.endswith("_count")}
            toks, st = await _blended_replay(eng, kinds, B, isl, vocab,
                                             n_arrivals=max(B, 4))
            await eng.close()
            return toks, st, d0

        toks, step_times, d0 = asyncio.run(run())
        s = engine.stats()
        dispatches = sum(
            v - d0.get(k, 0) for k, v in s.items()
            if k.startswith("dispatch_") and k.endswith("_count")
        )
        fused = s["mixed_steps"] > 0
        arms[name] = {
            "tokens_per_dispatch": round(toks / max(dispatches, 1), 3),
            "emitted_tokens": toks,
            "dispatches": dispatches,
            "mixed_steps": s["mixed_steps"],
            "split_steps": s["split_steps"],
            "mixed_coverage_frac": s["mixed_coverage_frac"],
            "mixed_rows": {
                k: s[f"mixed_rows_{k}"]
                for k in ("plain", "guided", "spec", "lora")
            },
            "padding_frac": s["mixed_padding_frac"] if fused
            else s["split_padding_frac"],
            "step_ms_p50": round(_pct(step_times["mixed" if fused
                                                 else "split"], 0.50), 2),
        }
        print(f"# {name}: {json.dumps(arms[name])}", file=sys.stderr)
    result = {
        "metric": f"engine_blend_{model}_bs{B}_{args.blend.replace(':', '-')}",
        "value": arms["unified"]["tokens_per_dispatch"],
        "unit": "tok/dispatch",
        "split_tokens_per_dispatch": arms["split"]["tokens_per_dispatch"],
        "mixed_coverage_frac": arms["unified"]["mixed_coverage_frac"],
        "mixed_rows": arms["unified"]["mixed_rows"],
        "mixed_padding_frac": arms["unified"]["padding_frac"],
        "mixed_step_ms_p50": arms["unified"]["step_ms_p50"],
        "split_step_ms_p50": arms["split"]["step_ms_p50"],
    }
    print(json.dumps(result))
    return 0


def main(argv: Optional[List[str]] = None):
    ap = argparse.ArgumentParser(description="dynamo-tpu engine benchmark")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--model", default=None)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--isl", type=int, default=128)
    ap.add_argument("--osl", type=int, default=128)
    ap.add_argument("--block", type=int, default=16)
    ap.add_argument("--pool-mode", choices=["scatter", "local"], default=None,
                    help="default: auto (local on TPU, scatter on CPU)")
    ap.add_argument("--unroll", type=int, default=0,
                    help="0 = auto (4 under local, 1 under scatter)")
    ap.add_argument("--quantize", choices=["int8"], default=None)
    ap.add_argument("--num-pages", type=int, default=None,
                    help="KV pool size override (floored at the batch's "
                    "working-set need) — the KV-write-strategy sweep axis")
    ap.add_argument("--spec", choices=["ngram"], default=None,
                    help="speculative decoding; the steady trace becomes "
                    "repetition-heavy so acceptance is measurable")
    ap.add_argument("--churn-s", type=float, default=None,
                    help="closed-loop churn window (0 disables)")
    ap.add_argument("--mixed", action="store_true",
                    help="unified-vs-split mixed-step comparison: replay a "
                    "mixed prefill+decode schedule on both paths and report "
                    "dispatches/step, padding-waste ratio, and step-time "
                    "p50/p99 (docs/ragged_attention.md)")
    ap.add_argument("--blend", default=None, metavar="KINDS",
                    help="with --mixed: colon-separated workload kinds to "
                    "blend into the replay (e.g. guided:lora:spec) — "
                    "reports tokens/dispatch, per-kind fused rows, and "
                    "mixed_coverage_frac vs the split reference")
    args = ap.parse_args(argv)

    if args.smoke:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        if "jax" in sys.modules:
            import jax

            jax.config.update("jax_platforms", "cpu")
            assert jax.devices()[0].platform == "cpu"

    model = args.model or ("tiny" if args.smoke else "llama3-3b")
    if not args.smoke:
        unavailable = ensure_backend(f"engine_decode_{model}")
        if unavailable is not None:
            print(json.dumps(unavailable))
            return 0
    vocab = 512 if model in ("tiny", "tiny-moe") else 128000
    B, isl, osl = args.batch, args.isl, args.osl
    if args.smoke:
        B, isl, osl = min(B, 8), min(isl, 64), min(osl, 32)
    churn_s = args.churn_s if args.churn_s is not None else (8.0 if args.smoke else 20.0)

    print(
        f"# engine bench: model={model} B={B} isl={isl} osl={osl} block={args.block}",
        file=sys.stderr,
    )
    if args.mixed:
        if args.blend:
            return run_blend_bench(args, model, vocab, B, isl, osl)
        return run_mixed_bench(args, model, vocab, B, isl, osl)
    engine = _make_engine(
        model, B, isl, osl, args.block,
        pool_mode=args.pool_mode, unroll=args.unroll, quantize=args.quantize,
        num_pages=args.num_pages, spec=args.spec,
    )
    rep = bool(args.spec)

    async def run():
        # warmup: compile all dispatch variants
        await _steady(engine, min(B, 2), isl, 8, vocab, seed=99, repetitive=rep)
        steady = await _steady(engine, B, isl, osl, vocab, repetitive=rep)
        churn = await _churn(engine, B, isl, osl, vocab, churn_s) if churn_s > 0 else {}
        await engine.close()
        return steady, churn

    steady, churn = asyncio.run(run())
    line = {**steady, **churn, "preemptions": engine.num_preemptions}
    print("# " + json.dumps(line), file=sys.stderr)
    import jax as _jax

    from bench_eff import efficiency_fields

    stats = engine.stats()
    result = {
        "metric": f"engine_decode_{model}_bs{B}_isl{isl}"
        + ("_int8" if args.quantize else "")
        + (f"_spec_{args.spec}" if args.spec else ""),
        **({
            "spec_mean_accepted_len": round(stats.get("spec_mean_accepted_len", 0.0), 2),
            "spec_num_draft_tokens": stats.get("spec_num_draft_tokens", 0),
            "spec_num_accepted_tokens": stats.get("spec_num_accepted_tokens", 0),
        } if args.spec else {}),
        "value": round(steady["decode_tok_s"], 1),
        "unit": "tok/s",
        "vs_baseline": baseline_ratio(steady["decode_tok_s"], model),
        "itl_ms": round(steady["itl_ms"], 2),
        "churn_tok_s": round(churn.get("churn_tok_s", 0.0), 1),
        "num_pages": engine.config.num_pages,
        **(efficiency_fields(
            model, steady["decode_tok_s"], B, isl + osl / 2, args.quantize,
        ) if _jax.local_devices()[0].platform == "tpu" else {}),
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
