"""TTFT breakdown profiler (round-3 verdict #4).

Round 3 measured 84.7 ms first-seq TTFT at isl=128 on llama3-3b — ~5 ms of
which is prefill compute. This tool decomposes the other ~80 ms into the
host-side stages so the fix lands where the time actually goes:

  rtt_noop        dispatch + host-fetch of a 1-element jitted add — the
                  pure dispatch/tunnel floor (the axon relay has a ~70 ms
                  RPC floor per sync; on-machine TPU runtimes show <1 ms)
  arg_transfer    host->device transfer of the isl-token prompt
  dispatch_only   prefill call returning WITHOUT a fetch: python arg
                  handling + executable-cache lookup + enqueue
  prefill_fetch   full prefill + first-token fetch (= raw TTFT)
  engine_ttft     the same request through JaxEngine.generate (adds
                  admission, scheduling, the step loop, emission)

Usage: python bench_ttft.py [--smoke] [--isl 128] [--model llama3-3b]
Prints a breakdown table on stderr and one JSON line on stdout.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path
from typing import List, Optional

REPO = Path(__file__).resolve().parent
sys.path.insert(0, str(REPO))

from bench import ensure_backend  # noqa: E402


def _median_ms(fn, n: int = 7) -> float:
    xs = []
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        xs.append((time.perf_counter() - t0) * 1000)
    return statistics.median(xs)


def main(argv: Optional[List[str]] = None):
    ap = argparse.ArgumentParser(description="TTFT breakdown profiler")
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--model", default=None)
    ap.add_argument("--isl", type=int, default=128)
    ap.add_argument("--reps", type=int, default=7)
    args = ap.parse_args(argv)

    if args.smoke:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        if "jax" in sys.modules:
            import jax

            jax.config.update("jax_platforms", "cpu")
            assert jax.devices()[0].platform == "cpu"

    model = args.model or ("tiny" if args.smoke else "llama3-3b")
    if not args.smoke:
        unavailable = ensure_backend(f"ttft_breakdown_{model}")
        if unavailable is not None:
            print(json.dumps(unavailable))
            return 0

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dynamo_tpu.engine.engine import _resolve_model
    from dynamo_tpu.engine.kv_cache import alloc_kv_arrays
    from dynamo_tpu.models import llama
    from dynamo_tpu.engine.sampling import SamplingParams, sample

    cfg = _resolve_model(model)
    isl = min(args.isl, 64) if args.smoke else args.isl
    PAGE = 64
    pages = (isl + PAGE) // PAGE + 1
    num_pages = pages + 1

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    kv_k, kv_v = alloc_kv_arrays(
        cfg.num_layers, num_pages, PAGE, cfg.num_kv_heads, cfg.head_dim, cfg.dtype
    )
    pt = jnp.asarray(1 + np.arange(pages, dtype=np.int32))[None, :]
    rng = np.random.RandomState(0)
    toks_host = rng.randint(3, cfg.vocab_size - 1, size=(1, isl)).astype(np.int32)
    pos_host = np.arange(isl, dtype=np.int32)[None, :]
    ctx0 = jnp.zeros((1,), jnp.int32)
    last = jnp.full((1,), isl - 1, jnp.int32)
    samp = SamplingParams.full(1, temperature=0.0)
    key = jax.random.PRNGKey(7)

    # ---- the stages ----
    noop = jax.jit(lambda x: x + 1)
    tiny = jnp.zeros((8,), jnp.int32)
    _ = jax.device_get(noop(tiny))  # compile

    def prefill_fn(p, kk, kv, t, po, tab, cl, li, s, k):
        logits, kk, kv = llama.prefill_forward_batched(
            p, cfg, t, po, kk, kv, tab, cl, li
        )
        return sample(logits, s, k), kk, kv

    prefill = jax.jit(prefill_fn)  # NO donation: repeated timing reuses kv
    first, _, _ = prefill(
        params, kv_k, kv_v, jnp.asarray(toks_host), jnp.asarray(pos_host),
        pt, ctx0, last, samp, key,
    )
    _ = jax.device_get(first)  # compile + warm

    rtt_noop = _median_ms(lambda: jax.device_get(noop(tiny)), args.reps)

    def xfer():
        a = jax.device_put(toks_host)
        jax.device_get(a.ravel()[0])

    arg_transfer = _median_ms(xfer, args.reps)

    dispatch_only = _median_ms(
        lambda: prefill(
            params, kv_k, kv_v, jnp.asarray(toks_host), jnp.asarray(pos_host),
            pt, ctx0, last, samp, key,
        ),
        args.reps,
    )

    def full():
        f, _, _ = prefill(
            params, kv_k, kv_v, jnp.asarray(toks_host), jnp.asarray(pos_host),
            pt, ctx0, last, samp, key,
        )
        jax.device_get(f)

    prefill_fetch = _median_ms(full, args.reps)

    # ---- engine path ----
    import asyncio

    from dynamo_tpu.engine import EngineConfig, JaxEngine
    from dynamo_tpu.runtime.engine import Context

    # reuse the already-resident weights: a second init_params inside the
    # engine would double weight residency and OOM a 16 GiB chip on 3b+
    eng = JaxEngine(EngineConfig(
        model=model, page_size=PAGE, num_pages=max(64, num_pages * 4),
        max_num_seqs=4, max_model_len=isl + 64,
    ), model_config=cfg, params=params)

    async def one_ttft() -> float:
        req = {
            "token_ids": toks_host[0].tolist(),
            "stop_conditions": {"max_tokens": 2, "ignore_eos": True},
        }
        t0 = time.perf_counter()
        async for item in eng.generate(req, Context()):
            if (item.get("data") or {}).get("token_ids"):
                return (time.perf_counter() - t0) * 1000
        return float("nan")

    async def drain():
        # leftover speculative decode blocks of a finished request occupy
        # the device queue; wait them out so each rep measures a CLEAN
        # arrival (the loaded-arrival case is the depth-capped queue delay,
        # reported separately by bench_engine/bench_e2e)
        while eng._inflight or any(s is not None for s in eng.slots):
            await asyncio.sleep(0.005)

    async def engine_rounds():
        await one_ttft()  # compile every engine variant
        await one_ttft()
        out = []
        for _ in range(args.reps):
            await drain()
            out.append(await one_ttft())
        return out

    engine_ttfts = asyncio.run(engine_rounds())
    asyncio.run(eng.close())
    engine_ttft = statistics.median(engine_ttfts)

    rows = {
        "rtt_noop_ms": round(rtt_noop, 2),
        "arg_transfer_ms": round(arg_transfer, 2),
        "dispatch_only_ms": round(dispatch_only, 2),
        "prefill_fetch_ms": round(prefill_fetch, 2),
        "engine_ttft_ms": round(engine_ttft, 2),
        "engine_overhead_ms": round(engine_ttft - prefill_fetch, 2),
        "compute_est_ms": round(prefill_fetch - rtt_noop, 2),
    }
    for k, v in rows.items():
        print(f"# {k:>20}: {v:8.2f}", file=sys.stderr)
    print(json.dumps({
        "metric": f"ttft_breakdown_{model}_isl{isl}",
        "value": rows["prefill_fetch_ms"],
        "unit": "ms",
        "vs_baseline": None,
        **rows,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
